"""Elastic driver: discovery-driven launch/relaunch with blacklist and
rank-stable assignments.

Reference: ``horovod/runner/elastic/driver.py`` (``ElasticDriver``: discovery
thread :181-201, stable rank assignment :233-275, worker spawn per slot
:277-295, blacklist + exit handling :297-313).

TPU-native design — every world change keeps SURVIVORS in-process
(reference: the reset loop, ``common/elastic.py:151-175``); the
generation-restart path is the backstop, not the norm:

* **Crashes recover in place** (round 5): the lost worker's peers catch
  ``HorovodInternalError``, the driver publishes a recovery world and
  respawns a REPLACEMENT for the dead rank onto free discovery capacity
  (shrinking to the survivors when capacity is gone); survivors
  re-rendezvous under their (possibly renumbered) ranks with parameters
  still in host memory. Viability requires every survivor to hold a
  fresh elastic-listener registration (proof it can apply a world doc);
  recoveries share the ``--reset-limit`` budget with restarts.
* **Planned capacity loss shrinks in place**: discovery dropping slots
  publishes the kept-worker world; dropped workers exit via the
  not-in-new-world path at their next commit.
* **Growth keeps survivors running** (VERDICT r1 #6): when discovery only
  ADDS capacity, the driver publishes a new world document (generation,
  size, per-rank env, fresh rendezvous port) to its KV server and spawns
  workers for the new slots only. Survivors pick the update up at their
  next ``state.commit()`` (``HostsUpdatedInterrupt`` → in-place re-init).
  Ranks are stable under growth, so survivors keep their shard
  assignments.
* **Restart backstop**: jobs without committed elastic state, completion
  races, reshuffled assignments, or too-few survivors terminate the
  generation and relaunch from the last ``HVD_ELASTIC_CKPT`` commit
  (stable ranks, failed hosts blacklisted).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from horovod_tpu.common.logging import get_logger
from horovod_tpu.runner.elastic import journal as journal_mod
from horovod_tpu.runner.elastic.discovery import HostDiscovery, HostManager
from horovod_tpu.runner.elastic.registration import (DRAINED, FAILURE,
                                                     SUCCESS, TERMINATED,
                                                     WorkerStateRegistry)
from horovod_tpu.runner.exec_run import (free_port, slot_command)
from horovod_tpu.runner.hosts import (HostInfo, SlotInfo,
                                      get_host_assignments)
from horovod_tpu.runner.safe_exec import (GRACEFUL_TERMINATION_TIME_S,
                                          safe_execute)

DISCOVERY_INTERVAL_S = 1.0


def loss_settle_s() -> float:
    """``HVD_TPU_LOSS_SETTLE_S``: how long the driver lets a worker loss
    SETTLE before planning recovery.  A correlated failure (a whole host
    group dying in one chaos window, a switch losing a rack) lands as
    several process exits milliseconds apart; recovering after the first
    one would plan a world containing peers that are already dead —
    a second recovery round at best, a spurious generation restart at
    worst.  The settle window collapses the burst into ONE re-mesh."""
    from horovod_tpu.common.config import env_float
    return max(0.0, env_float("LOSS_SETTLE_S", 0.3))


def drain_cooldown_s() -> float:
    """``HVD_TPU_DRAIN_COOLDOWN_S``: how long a drained host's capacity
    stays reserved after its preemption notice — long enough for the
    maintenance/preemption to actually happen, short enough that a
    repaired host rejoins promptly (expiry re-admits the capacity and
    the growth path re-spawns onto it)."""
    from horovod_tpu.common.config import env_float
    return max(0.0, env_float("DRAIN_COOLDOWN_S", 60.0))


def takeover_settle_s() -> float:
    """``HVD_TPU_DRIVER_TAKEOVER_SETTLE_S``: how long a takeover driver
    holds OFF recovery planning while adopted survivors re-register
    their elastic listeners.  The takeover KV starts with an empty
    ``notify`` scope — every registration the old driver held died with
    it — so a recovery planned in the first ticks would flunk the
    viability check and burn a generation restart, the exact outcome the
    takeover exists to avoid.  Survivors re-register at their next
    commit; the window only needs to outlast one step interval."""
    from horovod_tpu.common.config import env_float
    return max(0.0, env_float("DRIVER_TAKEOVER_SETTLE_S", 30.0))


def restart_cooldown_s() -> float:
    """``HVD_TPU_RESTART_COOLDOWN_S``: reservation window for an
    autopilot ``restart`` action (the hbm_growth planned restart,
    docs/OBSERVABILITY.md "Autopilot").  Unlike a preemption drain the
    host is HEALTHY — the restarted worker should respawn onto it as
    soon as the old process has exited and released its chip, so the
    default is seconds, not the drain cooldown's minute."""
    from horovod_tpu.common.config import env_float
    return max(0.0, env_float("RESTART_COOLDOWN_S", 5.0))


class _GenRuntime:
    """Mutable bookkeeping of ONE running generation — the poll loop's
    former closure state, promoted to an object so the drain-notice and
    autopilot-action handlers can be driver METHODS instead of blocks
    inlined in ``_run_generation``'s poll loop (PR 10's documented
    debt, paid down as the autopilot action channel landed in the same
    loop)."""

    def __init__(self, slots, gen: int, coord_addr: str,
                 coord_port: int) -> None:
        self.failure = threading.Event()
        self.teardown = threading.Event()  # restart path: kill survivors
        self.worker_lost = threading.Event()  # crash: in-place shrink 1st
        self.fail_lock = threading.Lock()
        # per-worker bookkeeping keyed by (spawn_generation, rank): ranks
        # are reused across in-generation worlds (shrink renumbers,
        # growth appends), so the rank alone is not a stable identity
        self.results: Dict[tuple, str] = {}
        self.lost_keys: set = set()
        # keys whose exit was classified as the ORIGINATING failure (not
        # a casualty of someone else's crash): only these charge their
        # host's crash budget — a cascade must not blocklist every host
        # whose healthy workers died from the collective error
        self.originators: set = set()
        self.host_crashes: Dict[str, int] = {}
        # workers a capacity-loss shrink dropped from the world: their
        # exit (the not-in-new-world path) is EXPECTED, not a crash
        self.expected_exits: set = set()
        # workers a preemption drain (or an autopilot action) planned
        # out of the world: EXPECTED exits recorded DRAINED — never
        # FAILURE, never a host_crashes charge, never blocklist evidence
        self.drained_exits: set = set()
        # drain-notice / action-request tokens already acted on; tokens
        # are (scope, key, payload) so the two KV scopes cannot collide
        self.handled_tokens: set = set()
        # tokens whose planned world was not viable yet (min_np, last
        # host, completion race): token -> (next_try, delay).  The world
        # can BECOME viable — discovery adds a host — so the request is
        # retried with backoff instead of burned.
        self.deferred_tokens: dict = {}
        self.threads: Dict[tuple, threading.Thread] = {}
        self.slot_by_key: Dict[tuple, object] = {}
        self.current_rank: Dict[tuple, int] = {}  # rank in CURRENT world
        self.slots = slots
        self.np = len(slots)
        # the job is DONE when every worker of the generation it started
        # with succeeds (minus crash-shrunken ones) — growth-spawned
        # stragglers whose world the survivors never joined (completion
        # raced the scale-up) must not hold the driver hostage
        self.essential_keys: List[tuple] = [(gen, s.rank) for s in slots]
        self.essential_gen = gen
        # the generation of the most recently PUBLISHED world — what the
        # workers' HVD_ELASTIC_GENERATION reads after they adopt it, and
        # therefore what their drain notices / action requests carry.
        # Tracked separately from essential_gen because in-place GROWTH
        # publishes a new generation (rank numbering unchanged — the
        # stable-assignment check guarantees it) without touching the
        # essential set.
        self.world_gen = gen
        # the generation of the last publish that CHANGED the rank
        # numbering: growth keeps numbering stable, so notices stamped
        # anywhere in [numbering_gen, world_gen] still name a valid
        # rank; in-place shrink recoveries compact ranks and bump it
        self.numbering_gen = gen
        self.coord_addr = coord_addr
        self.coord_port = coord_port
        self.spawn = None  # bound by _run_generation


#: autopilot action kinds the driver honors, mapped to whether the
#: target's host capacity is reserved for the full drain cooldown
#: (True: the host is suspect — place the replacement elsewhere) or
#: only the short restart window (False: the host is healthy, the
#: replacement should respawn onto it as soon as the chip is free).
#: ``quarantine`` (ISSUE 13) additionally BLOCKLISTS the host with the
#: action's evidence once the planned re-mesh succeeds — the one
#: planned exit that is held against the hardware, because silent data
#: corruption is a device property, not a scheduling accident.
_ACTION_KINDS = {"drain": True, "restart": False, "quarantine": True}


def _finished_thread() -> threading.Thread:
    """A dead, already-joined Thread object.  Takeover rebuilds preload
    journal-replayed exits into ``_GenRuntime.threads`` — membership
    code indexes ``threads[k].is_alive()`` without a guard, so every
    bookkept key needs a Thread whose liveness answers correctly."""
    t = threading.Thread(target=lambda: None, daemon=True)
    t.start()
    t.join()
    return t


class ElasticDriver:
    def __init__(self, discovery: HostDiscovery, command: List[str],
                 min_np: int = 1, max_np: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None,
                 reset_limit: Optional[int] = None,
                 verbose: bool = False,
                 ckpt_dir: Optional[str] = None,
                 target_np: Optional[int] = None,
                 remote_exec=None,
                 world_secret: Optional[bytes] = None,
                 timestamp_output: bool = False,
                 start_timeout: Optional[float] = None,
                 elastic_timeout: Optional[float] = None,
                 journal_dir: Optional[str] = None,
                 takeover: bool = False) -> None:
        # remote_exec(slot, command, worker_env, events) -> rc replaces the
        # local/ssh exec when the cluster reaches hosts another way — e.g.
        # Spark tasks acting as host agents (spark/elastic.py). The
        # reference's analog is routing exec through its task services
        # instead of ssh (spark/gloo_run.py). world_secret lets such a
        # caller pre-share the world-doc HMAC key over its own trusted
        # channel instead of shipping it in worker envs over the network.
        self._remote_exec = remote_exec
        self._preshared_secret = world_secret
        self._timestamp_output = timestamp_output
        self._hosts = HostManager(discovery)
        self._command = command
        self._min_np = min_np
        self._max_np = max_np
        self._target_np = target_np
        self._env = dict(env if env is not None else os.environ)
        self._registry = WorkerStateRegistry(reset_limit)
        self._verbose = verbose
        self._ckpt_dir = ckpt_dir or tempfile.mkdtemp(prefix="hvd_elastic_")
        # reference: --start-timeout bounds the initial min-host wait,
        # --elastic-timeout the re-scale waits after a generation ends
        # (an explicit 0 means "fail fast", so only None gets the default)
        self._start_timeout = 600.0 if start_timeout is None \
            else start_timeout
        self._elastic_timeout = 600.0 if elastic_timeout is None \
            else elastic_timeout
        self._stop = threading.Event()
        self._hosts_changed = threading.Event()
        self._generation = 0
        # world-document KV: survivors poll it at commit for growth resync.
        # Docs are HMAC-signed — workers apply env/coordinator changes from
        # them, and the KV port is open to the network.
        import secrets as _secrets
        import socket as _socket
        from horovod_tpu.runner.http_kv import KVStoreServer
        self._kv = KVStoreServer()
        self._world_secret = self._preshared_secret or \
            _secrets.token_bytes(16)
        # the KV runs on THIS driver machine; remote workers need an
        # address that routes back here, not rank 0's host. gethostname,
        # not getfqdn: the latter can resolve to 'localhost' → ::1 while
        # the KV server is IPv4-only (see spark/elastic.py kv_addr)
        self._driver_addr = _socket.gethostname()
        # -- control-plane journal + crash takeover (docs/ELASTIC.md
        # "Driver failover & takeover") --------------------------------
        self._journal: Optional[journal_mod.DriverJournal] = None
        self._replay: Optional[journal_mod.ReplayState] = None
        self._takeover = bool(takeover)
        self._poll_tick = 0
        # rank -> addr last journaled as a "notify" record; the poll
        # loop journals only registration CHANGES, not every tick
        self._journaled_notify: Dict[str, str] = {}
        jd = journal_dir or journal_mod.journal_dir()
        kv_port: Optional[int] = None
        if self._takeover:
            if not jd:
                raise journal_mod.TakeoverRefused(
                    "takeover requested but no journal directory is "
                    "configured: set HVD_TPU_DRIVER_JOURNAL_DIR "
                    "(docs/ELASTIC.md 'Driver failover & takeover')")
            state = journal_mod.load(
                os.path.join(jd, journal_mod.JOURNAL_NAME))
            state.check_takeover()  # TakeoverRefused propagates: the
            # supervisor/operator falls back to the generation-restart
            # backstop instead of risking a stale world
            self._replay = state
            meta = state.meta
            # the fleet's worker envs carry the OLD secret/ckpt/address:
            # the takeover driver must become that identity, not mint a
            # fresh one the workers would reject
            if meta.get("secret") and self._preshared_secret is None:
                self._world_secret = bytes.fromhex(meta["secret"])
            if meta.get("ckpt_dir"):
                self._ckpt_dir = meta["ckpt_dir"]
            if meta.get("driver_addr"):
                self._driver_addr = meta["driver_addr"]
            if meta.get("kv_port"):
                kv_port = int(meta["kv_port"])
            self._generation = state.world_gen + 1
        if jd:
            self._journal = journal_mod.DriverJournal(jd)
            # WAL worker listener registrations AS THEY ARRIVE: the poll
            # loop may be stalled (or die this very tick) between a
            # worker's first commit and the next tick, and a
            # registration the journal never saw is a registration the
            # takeover driver cannot restore
            self._kv.on_put = self._observe_kv_put
        # rebinds the previously advertised port on takeover (workers
        # keep polling driver_addr:kv_port; SO_REUSEADDR rides out the
        # dead listener's TIME_WAIT)
        self._kv.start(port=kv_port)
        if self._journal is not None and not self._takeover:
            self._journal.append(
                "job_open", secret=self._world_secret.hex(),
                kv_port=self._kv.port, driver_addr=self._driver_addr,
                ckpt_dir=self._ckpt_dir, min_np=self._min_np,
                max_np=self._max_np, target_np=self._target_np,
                pid=os.getpid(), ts=journal_mod.now_wall())
        self._init_driver_chaos()

    # -- journal plumbing ----------------------------------------------------
    def _journal_append(self, rtype: str, critical: bool = False,
                        **fields) -> None:
        """Write-ahead append; no-op without a journal.  ``critical``
        records (world publishes, takeover stamps) propagate I/O
        failure — a driver that cannot journal the decisions a takeover
        depends on must not keep making them; everything else degrades
        to a warning (losing a spawn pid costs the takeover an adopted
        monitor, not correctness)."""
        if self._journal is None:
            return
        try:
            self._journal.append(rtype, **fields)
        except Exception:
            if critical:
                raise
            get_logger().warning(
                "driver journal append (%s) failed", rtype, exc_info=True)

    def _observe_kv_put(self, scope: str, key: str,
                        value: bytes) -> None:
        """KV write observer (HTTP PUT path, called before the 200):
        journals worker listener registrations synchronously so they
        are durable the moment the worker is told they took."""
        if scope != "notify" or self._journal is None:
            return
        addr = value.decode("utf-8", errors="replace") \
            if isinstance(value, (bytes, bytearray)) else str(value)
        if self._journaled_notify.get(str(key)) != addr:
            self._journal_append("notify", rank=str(key), addr=addr)
            self._journaled_notify[str(key)] = addr

    def _journal_notify_observations(self) -> None:
        """Journal worker listener registrations as the poll loop sees
        them land in the ``notify`` scope.  A worker whose in-flight KV
        get simply retried across a short driver outage never observes
        the takeover and never re-registers — the journal is the only
        place the registration survives, and a takeover driver restores
        it so in-place recovery stays viable (docs/ELASTIC.md "Driver
        failover & takeover")."""
        if self._journal is None:
            return
        for rank, raw in self._kv.scope("notify").items():
            addr = raw.decode("utf-8", errors="replace") \
                if isinstance(raw, (bytes, bytearray)) else str(raw)
            if self._journaled_notify.get(str(rank)) != addr:
                self._journal_append("notify", rank=str(rank), addr=addr)
                self._journaled_notify[str(rank)] = addr

    def _journal_token(self, token) -> None:
        """Journal a handled drain-notice/action token so a takeover
        driver never re-handles a request the dead driver already acted
        on (or deliberately burned)."""
        scope, key, raw = token
        self._journal_append(
            "token", scope=scope, key=key,
            raw=raw.decode("utf-8", errors="replace")
            if isinstance(raw, (bytes, bytearray)) else str(raw))

    def _init_driver_chaos(self) -> None:
        """Arm ONLY the fault plan's ``driver``-seam rules, in a private
        engine.  The module-level ``chaos.install()`` is the workers':
        its rules default to every rank and ``_env_rank()`` resolves to
        0 in this process, so installing globally here would fire
        worker-targeted faults inside the control plane.  A typo'd plan
        raises ``FaultPlanError`` out of the constructor — a chaos run
        must fail loudly, not run fault-free."""
        self._chaos = None
        from horovod_tpu.chaos.plan import load_plan_from_env
        plan = load_plan_from_env()
        if plan is None:
            return
        rules = [r for r in plan.rules if r.seam == "driver"]
        if not rules:
            return
        import dataclasses as _dc
        from horovod_tpu.chaos import ChaosEngine
        self._chaos = ChaosEngine(_dc.replace(plan, rules=rules), rank=0)
        get_logger().warning(
            "chaos: %d driver-seam rule(s) armed in the elastic driver",
            len(rules))

    # -- discovery thread (reference: driver.py:181-201) --------------------
    def _discovery_loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self._hosts.update_available_hosts():
                    self._hosts_changed.set()
            except Exception as e:  # discovery script hiccup: keep going
                get_logger().warning("host discovery failed: %s", e)
            time.sleep(DISCOVERY_INTERVAL_S)

    def _wait_for_min_hosts(self, timeout: float = 600.0) -> None:
        deadline = time.time() + timeout
        consecutive_failures = 0
        while time.time() < deadline:
            try:
                self._hosts.update_available_hosts()
                consecutive_failures = 0
            except Exception as e:  # transient discovery hiccup: keep going
                consecutive_failures += 1
                get_logger().warning("host discovery failed: %s", e)
                if consecutive_failures >= 5:
                    # permanent misconfiguration (bad script path etc.):
                    # surface the real error instead of spinning to timeout
                    raise RuntimeError(
                        "host discovery failed 5 times in a row; check the "
                        f"discovery script: {e}") from e
            if self._hosts.slot_count() >= self._min_np:
                return
            time.sleep(DISCOVERY_INTERVAL_S)
        raise TimeoutError(
            f"needed {self._min_np} slots, found {self._hosts.slot_count()}")

    # -- world publication ---------------------------------------------------
    def _cap_np(self) -> int:
        return min(self._target_np or self._hosts.slot_count(),
                   self._max_np or self._hosts.slot_count(),
                   self._hosts.slot_count())

    def _publish_world(self, gen: int, slots, coord_addr: str,
                       coord_port: int, keyed_slots=None,
                       extra=None, runtime=None) -> None:
        """Publish a signed world doc. ``slots`` keys the doc by each
        slot's own (stable) rank — the growth case. ``keyed_slots``
        overrides with an explicit ``{lookup_rank: env}`` mapping — the
        shrink case, where survivors look themselves up by their OLD
        rank but adopt a smaller new one from the env.  ``extra`` merges
        additional signed fields into the doc (the ``drain`` stamp of a
        planned preemption re-mesh, which survivors use to label their
        re-mesh episode ``preemption_drain``).  ``runtime`` is the
        post-publish generation bookkeeping (:meth:`_runtime_record`)
        journaled WITH the doc — the write-ahead rule: the fsync'd
        journal line lands BEFORE the KV put, so the journal is always
        at least as new as anything the fleet saw and a takeover can
        complete an interrupted publish but never resurrect a stale
        world."""
        import json
        from horovod_tpu.elastic import world_doc_signature
        doc = {"generation": gen, "size": len(slots),
               "coord_addr": coord_addr, "coord_port": coord_port,
               "slots": keyed_slots if keyed_slots is not None
               else {str(s.rank): s.to_env() for s in slots}}
        if extra:
            doc.update(extra)
        doc["sig"] = world_doc_signature(self._world_secret, doc)
        body = json.dumps(doc).encode()
        if self._journal is not None:
            self._journal_append("world_publish", critical=True,
                                 doc=doc, **(runtime or {}))
            try:
                # world-publish boundaries are the one safe compaction
                # point: the canonical record set re-emits this world
                self._journal.maybe_compact()
            except Exception:
                get_logger().warning("driver journal compaction failed",
                                     exc_info=True)
        self._kv.put("world", "current", body)
        self._push_world(body)

    @staticmethod
    def _runtime_record(gen: int, slots, coord_addr: str, coord_port: int,
                        essential_keys, current_rank, numbering_gen: int,
                        essential_gen: int, expected_exits=(),
                        drained_exits=()) -> dict:
        """The generation bookkeeping a ``world_publish`` record carries
        — everything :meth:`_rebuild_generation` needs to reconstruct a
        live :class:`_GenRuntime` without guessing.  Pure JSON-able
        data: (gen, rank) key tuples become 2-lists, slots become their
        dataclass dicts."""
        import dataclasses as _dc
        return {
            "world_gen": gen,
            "numbering_gen": numbering_gen,
            "essential_gen": essential_gen,
            "np": len(slots),
            "coord_addr": coord_addr,
            "coord_port": coord_port,
            "slots": [_dc.asdict(s) for s in slots],
            "essential_keys": [list(k) for k in essential_keys],
            "current_rank": [[list(k), r]
                             for k, r in current_rank.items()],
            "expected_exits": [list(k) for k in expected_exits],
            "drained_exits": [list(k) for k in drained_exits],
        }

    def _push_world(self, body: bytes) -> None:
        """Push the published doc to every registered worker listener
        (reference: WorkerNotificationService push,
        ``runner/elastic/worker.py:46+``). Best-effort with short
        timeouts: a worker that missed the push still finds the doc by
        polling the KV at its next commit."""
        from horovod_tpu.runner.http_kv import kv_put

        def push(host: str, port: int) -> None:
            try:
                kv_put(host, port, "world", "current", body, timeout=5.0,
                       site="elastic.world_push")
            except OSError as e:
                get_logger().debug("world push to %s:%d failed: %s",
                                   host, port, e)

        for _rank, addr in self._kv.scope("notify").items():
            try:
                # the KV PUT surface is open to the network: malformed
                # registrations must be skipped, never crash the driver
                host, _, port = addr.decode().rpartition(":")
                port_num = int(port)
            except (UnicodeDecodeError, ValueError):
                get_logger().warning("ignoring malformed notify "
                                     "registration for rank %s", _rank)
                continue
            threading.Thread(target=push, args=(host, port_num),
                             daemon=True).start()

    # -- in-place crash recovery --------------------------------------------
    def _try_inplace_recovery(self, survivors, results, threads,
                              slot_by_key, current_rank, target_np,
                              host_crashes, charge_reset=True,
                              drain=None, gen_runtime=None):
        """A worker died mid-generation: publish a new world around the
        SURVIVORS so they re-rendezvous IN PLACE (params stay in host
        memory, PIDs unchanged — reference: the reset loop after
        HorovodInternalError, ``common/elastic.py:151-175``) instead of
        paying a process restart + checkpoint reload. Replacement
        workers for the lost ranks are respawned onto free discovery
        capacity (the reference spawns missing ranks the same way); if
        capacity is gone (host dead / removed), the world SHRINKS to the
        survivors + whatever fits. Hosts that have already eaten as many
        crashes as they have slots get no replacements.

        Returns ``(new_slots, generation, replacement_slots, coord_addr,
        coord_port)`` on success, ``None`` when not viable — too few
        survivors+capacity, an essential worker already FINISHED (its
        result was published under the old generation; the restart path
        handles that completion race), or the --reset-limit budget is
        spent. ``charge_reset=False`` (planned capacity-loss shrinks)
        leaves the crash budget untouched — routine autoscaler
        downscales must never exhaust it."""
        if any(results.get(k) is not None or not threads[k].is_alive()
               for k in survivors):
            get_logger().info("in-place recovery not viable: an "
                              "essential worker already finished")
            return None
        # every survivor must have REGISTERED its notification listener
        # (done at its first elastic commit): that proves it runs an
        # elastic.run loop able to apply a new world doc. A worker still
        # inside hvd.init — or a job without elastic state at all — can
        # only be recovered by the generation-restart path; publishing a
        # world it will never read would deadlock the rendezvous.
        notify = {str(k) for k in self._kv.scope("notify")}
        unready = [k for k in survivors
                   if str(current_rank[k]) not in notify]
        if unready:
            get_logger().info(
                "in-place recovery not viable: survivors %s have no "
                "elastic listener registration (no committed elastic "
                "state)", [current_rank[k] for k in unready])
            return None
        surv_on: Dict[str, int] = {}
        for k in survivors:
            h = slot_by_key[k].hostname
            surv_on[h] = surv_on.get(h, 0) + 1
        # replacements go onto free capacity of healthy discovered hosts
        hosts_now = self._hosts.current_hosts()
        placement: List[str] = []
        n_repl = max(0, target_np - len(survivors))
        for h in hosts_now:
            if len(placement) >= n_repl:
                break
            if host_crashes.get(h.hostname, 0) >= h.slots:
                continue  # this host just keeps killing workers
            free = h.slots - surv_on.get(h.hostname, 0)
            placement.extend([h.hostname] * max(0, min(
                free, n_repl - len(placement))))
        new_np = len(survivors) + len(placement)
        if new_np < max(self._min_np, 1):
            get_logger().info(
                "in-place recovery not viable: %d survivors + %d "
                "replacements < min_np %d", len(survivors),
                len(placement), self._min_np)
            return None
        if charge_reset:
            # charged only once viability is established — a non-viable
            # attempt already pays for its generation restart
            self._registry.note_reset()
            # the --reset-limit budget belongs to the JOB: journaled so
            # a takeover driver inherits the spent count instead of
            # handing a crash-looping worker a fresh allowance
            self._journal_append("reset",
                                 count=self._registry.reset_count)
            if self._registry.reset_limit_reached():
                get_logger().info("in-place recovery not viable: reset "
                                  "limit reached")
                return None
        # per-host entries: survivors (in current-rank order) first, then
        # replacements — block assignment then aligns host-wise
        host_order: List[str] = []
        entries: Dict[str, list] = {}
        for k in sorted(survivors, key=lambda k: current_rank[k]):
            h = slot_by_key[k].hostname
            if h not in entries:
                host_order.append(h)
                entries[h] = []
            entries[h].append(k)
        for h in placement:
            if h not in entries:
                host_order.append(h)
                entries[h] = []
            entries[h].append(None)  # replacement marker
        hosts2 = [HostInfo(h, len(entries[h])) for h in host_order]
        new_slots = get_host_assignments(hosts2, new_np)
        flat = [e for h in host_order for e in entries[h]]
        keyed = {}
        replacements = []
        for e, ns in zip(flat, new_slots):
            if e is None:
                replacements.append(ns)
                continue
            assert ns.hostname == slot_by_key[e].hostname, (e, ns)
            # survivors look the doc up by the rank they CURRENTLY hold;
            # the env inside hands them their new one
            keyed[str(current_rank[e])] = ns.to_env()
            current_rank[e] = ns.rank
        coord_port = free_port()
        coord_addr = "127.0.0.1" if new_slots[0].hostname in (
            "localhost", "127.0.0.1") else new_slots[0].hostname
        gen = self._generation
        self._generation += 1
        get_logger().info(
            "elastic generation %d (%s): np=%d "
            "(%d survivors + %d replacements)", gen,
            "planned preemption drain" if drain
            else "in-place crash recovery", new_np,
            len(survivors), len(replacements))
        extra = {"drain": drain} if drain else {}
        from horovod_tpu import tracing
        if drain is None:
            # a REACTIVE recovery has no inbound context to continue
            # (the planned path's drain stamp carries the notice's) —
            # root one here so every survivor's re-mesh episode still
            # shares a single trace id with this publish
            ctx = tracing.new_trace("elastic")
            if ctx is not None:
                extra["traceparent"] = ctx.traceparent
        # journaled runtime: the post-recovery world's bookkeeping —
        # survivors under their NEW ranks plus the replacements the
        # caller is about to spawn (exactly what the caller sets as
        # essential_keys after we return)
        essential2 = sorted(survivors, key=lambda k: current_rank[k]) + \
            [(gen, s.rank) for s in replacements]
        cr2 = {k: current_rank[k] for k in survivors}
        cr2.update({(gen, s.rank): s.rank for s in replacements})
        if gen_runtime is not None:
            with gen_runtime.fail_lock:
                exp = set(gen_runtime.expected_exits)
                drn = set(gen_runtime.drained_exits)
        else:
            exp, drn = set(), set()
        self._publish_world(gen, new_slots, coord_addr, coord_port,
                            keyed_slots=keyed, extra=extra or None,
                            runtime=self._runtime_record(
                                gen, new_slots, coord_addr, coord_port,
                                essential2, cr2, gen, gen, exp, drn))
        # driver-side half of the re-mesh timeline: the survivors
        # measure their own phases (hvd_remesh_seconds); the driver
        # stamps WHEN it published the recovery world, so a merged
        # flight view can attribute the workers' failure_detect wait
        from horovod_tpu.diagnostics.flight_recorder import record_event
        doc_ctx = tracing.decode((drain or {}).get("traceparent")) \
            if drain else ctx
        record_event("remesh_driver_published", generation=gen,
                     np=new_np, survivors=len(survivors),
                     replacements=len(replacements),
                     charge_reset=charge_reset,
                     **tracing.fields(doc_ctx))
        # registrations are stale the moment ranks renumber: survivors
        # re-register at their first commit in the new world, and a crash
        # BEFORE that commit conservatively takes the restart path
        self._kv.clear("notify")
        self._journaled_notify.clear()
        # so are drain notices: a notice names the rank its publisher
        # held in the OLD numbering — left behind, an unhandled notice
        # would match whichever innocent worker inherits that rank
        self._kv.clear("drain")
        # and so are autopilot action requests, for the same reason: the
        # rank an action targets is only meaningful in the numbering
        # whose finding fired it
        self._kv.clear("action")
        # completion receipts are stamped with rank + generation: after
        # a renumbering publish a stale receipt could name an innocent
        # worker's new rank, so they die with the old numbering too
        self._kv.clear("result")
        return new_slots, gen, replacements, coord_addr, coord_port

    # -- drain notices & autopilot actions (poll-loop handlers) -------------
    def _scan_scope(self, g: _GenRuntime, scope: str, label: str):
        """THE one validation core for worker→driver request scopes
        (drain notices and autopilot actions share it — a fix to the
        gating below must never apply to one and silently diverge the
        other).  For each entry: skip already-handled tokens and those
        inside their no-viable-world backoff window; burn (never retry)
        malformed JSON; require the stamped generation inside
        ``[numbering_gen, world_gen]`` — published under another rank
        NUMBERING, matching it against the current one could doom an
        innocent worker, while growth publishes bump the generation but
        keep the numbering (stable-assignment check) so anything since
        the last RENUMBERING publish is still valid; out-of-window
        entries are left unhandled (not burned): the next re-mesh
        clears the scope, worst case the worker dies reactively.
        Finally resolve the named rank to a live essential worker; a
        miss (already gone or renumbered) burns the token as stale.
        Returns ``[(token, doc, origin key, named rank)]``."""
        import json as _json
        out = []
        for key, raw in self._kv.scope(scope).items():
            token = (scope, key, raw)
            if token in g.handled_tokens:
                continue
            deferred = g.deferred_tokens.get(token)
            if deferred and deferred[0] > time.monotonic():
                continue  # no-viable-world backoff window
            try:
                doc = _json.loads(raw)
                if not isinstance(doc, dict):
                    raise TypeError(f"{label} is not an object")
                nrank = int(doc.get("rank"))
                ngen = int(doc.get("generation", -1))
            except (ValueError, TypeError):
                g.handled_tokens.add(token)  # never retried
                self._journal_token(token)
                get_logger().warning(
                    "ignoring malformed %s %r", label, key)
                continue
            if not g.numbering_gen <= ngen <= g.world_gen:
                continue  # another numbering (docstring above)
            origin = next(
                (k for k in g.essential_keys
                 if g.current_rank.get(k) == nrank
                 and g.results.get(k) is None
                 and g.threads[k].is_alive()), None)
            if origin is None:
                g.handled_tokens.add(token)
                self._journal_token(token)
                continue  # already gone or renumbered: stale
            out.append((token, doc, origin, nrank))
        return out

    def _scan_drain_notices(self, g: _GenRuntime):
        """Collect actionable drain notices from the KV ``drain`` scope
        (docs/ELASTIC.md "Proactive drain & preemption"): a doomed
        worker's PreemptionWatcher published ``drain/<rank>``; plan its
        world out AROUND it instead of waiting for the death +
        transport-timeout detection the reactive path pays.  Returns
        ``(doomed keys, notice meta, tokens)``."""
        doomed: set = set()
        notice_meta: list = []
        tokens: list = []
        for token, notice, origin, nrank in self._scan_scope(
                g, "drain", "drain notice"):
            tokens.append(token)
            if notice.get("scope") == "host":
                # host-wide maintenance dooms every worker there
                h = g.slot_by_key[origin].hostname
                doomed |= {k for k in g.essential_keys
                           if g.slot_by_key[k].hostname == h
                           and g.results.get(k) is None
                           and g.threads[k].is_alive()}
            else:
                doomed.add(origin)
            entry = {"rank": nrank,
                     "host": g.slot_by_key[origin].hostname,
                     "source": notice.get("source", "unknown")}
            if isinstance(notice.get("traceparent"), str):
                # the publisher's trace context rides the notice doc;
                # the handling and the published world continue it
                entry["traceparent"] = notice["traceparent"]
            notice_meta.append(entry)
        return doomed, notice_meta, tokens

    def _scan_action_requests(self, g: _GenRuntime):
        """Collect actionable autopilot requests from the KV ``action``
        scope (ISSUE 12; docs/OBSERVABILITY.md "Autopilot"): a policy
        engine's fired remediation asked the driver to plan a worker
        out of the world — ``drain`` (sick host: reserve its capacity
        for the full cooldown) or ``restart`` (healthy host: final
        durable commit, then respawn in place after the short restart
        window).  Validation is :meth:`_scan_scope`, shared with the
        drain notices; an unknown action kind is burned here.  Returns
        ``{kind: (doomed keys, meta, tokens)}``."""
        groups = {kind: (set(), [], []) for kind in _ACTION_KINDS}
        for token, req, origin, nrank in self._scan_scope(
                g, "action", "autopilot action"):
            kind = req.get("action")
            if kind not in _ACTION_KINDS:
                g.handled_tokens.add(token)
                self._journal_token(token)
                get_logger().warning(
                    "ignoring autopilot action %r with unknown kind %r",
                    token[1], kind)
                continue
            doomed, meta, tokens = groups[kind]
            doomed.add(origin)
            tokens.append(token)
            entry = {"rank": nrank,
                     "host": g.slot_by_key[origin].hostname,
                     "source": "autopilot",
                     "policy": req.get("policy"),
                     "action": kind}
            if isinstance(req.get("traceparent"), str):
                # finding → decision → action doc: the trace continues
                # through the driver's handling into the re-mesh
                entry["traceparent"] = req["traceparent"]
            if isinstance(req.get("evidence"), dict):
                # quarantine requests carry the canary digests that
                # convicted the rank — recorded with the blocklist
                entry["evidence"] = req["evidence"]
            meta.append(entry)
        return groups

    def _plan_world_out(self, g: _GenRuntime, doomed: set,
                        notice_meta: list, tokens: list,
                        cooldown: float, event_kind: str):
        """Plan the current world around ``doomed`` (shared by drain
        notices and autopilot actions): reserve the doomed capacity,
        mark the exits DRAINED, publish the survivor world, spawn
        replacements onto free capacity — or, when no viable world
        exists, REVERT every piece of that bookkeeping and retry the
        request with backoff (reactive recovery covers an actual
        death).  Returns ``"planned"`` when the survivor world was
        published, ``"retry"`` when no viable world existed and the
        request was re-armed with backoff — both truthy: the tick is
        consumed and the caller ``continue``s — or False when the
        request was deferred untouched (workers still registering
        their elastic listeners)."""
        # the planned path needs every involved worker able to APPLY a
        # world doc (elastic listener registered, i.e. it has committed
        # once).  A request racing the job's first commits — a
        # preemption can announce itself during hvd.init — is DEFERRED
        # to a later tick, not burned on a generation restart.
        notify = {str(r) for r in self._kv.scope("notify")}
        involved = set(doomed) | {
            k for k in g.essential_keys
            if k not in doomed and g.results.get(k) is None
            and g.threads[k].is_alive()}
        if any(str(g.current_rank[k]) not in notify for k in involved):
            return False
        g.handled_tokens.update(tokens)
        # the driver's handling is a CHILD span of the notice/action
        # that asked for it (docs/OBSERVABILITY.md "Causal tracing");
        # the drain-stamped world carries the context onward so every
        # survivor's re-mesh episode joins the same trace
        from horovod_tpu import tracing
        hctx = None
        for m in notice_meta:
            hctx = tracing.child(
                tracing.decode(m.get("traceparent")), "elastic")
            if hctx is not None:
                break
        by_host: Dict[str, int] = {}
        for k in doomed:
            h = g.slot_by_key[k].hostname
            by_host[h] = by_host.get(h, 0) + 1
        for h, n in by_host.items():
            # reserve the doomed capacity so replacement placement
            # cannot land back on it before the cooldown re-admits it
            # (a drain's host announced its own death; a restart's is
            # healthy and re-admits within seconds)
            self._hosts.drain(h, n, cooldown)
            # wall-stamped so a takeover restores only the REMAINING
            # window (discovery.restore_state re-ages it)
            self._journal_append("drain", host=h, slots=n,
                                 remaining_s=cooldown,
                                 ts=journal_mod.now_wall())
        with g.fail_lock:
            # BEFORE the publish (same reason as the shrink path): the
            # doomed worker can read the pushed doc and exit before
            # this loop resumes, and that exit is DRAINED, never a
            # crash
            g.expected_exits.update(doomed)
            g.drained_exits.update(doomed)
        survivors = [k for k in g.essential_keys if k not in doomed]
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event(
            event_kind,
            notices=notice_meta,
            drained_ranks=sorted(g.current_rank[k] for k in doomed),
            hosts=sorted(by_host), cooldown_s=cooldown,
            **tracing.fields(hctx))
        get_logger().warning(
            "%s %s: planning world around doomed rank(s) %s (hosts %s "
            "reserved for %.0fs)", event_kind, notice_meta,
            sorted(g.current_rank[k] for k in doomed),
            sorted(by_host), cooldown)
        recovered = self._try_inplace_recovery(
            survivors, g.results, g.threads, g.slot_by_key,
            g.current_rank, self._cap_np(), g.host_crashes,
            charge_reset=False,
            drain={"ranks": sorted(g.current_rank[k] for k in doomed),
                   "hosts": sorted(by_host),
                   "sources": sorted({m["source"]
                                      for m in notice_meta}),
                   **({"traceparent": hctx.traceparent}
                      if hctx is not None else {})},
            gen_runtime=g)
        if recovered is None:
            # no viable planned world (the doomed host was the last
            # one, min_np would be violated, or a completion race): the
            # request is ADVISORY — the worker has not died, and may
            # never.  Tearing the generation down here would turn
            # advance notice into a guaranteed restart the reactive
            # path never pays, so revert the bookkeeping and fall back
            # to reactive recovery instead.
            with g.fail_lock:
                g.expected_exits.difference_update(doomed)
                g.drained_exits.difference_update(doomed)
                # a doomed worker that exited DURING the failed
                # planning attempt was classified an expected DRAINED
                # exit, so run_slot never marked it lost — re-mark it
                # here or no recovery would ever be planned for a
                # genuinely dead worker and the generation would wedge
                gone = [k for k in doomed
                        if g.results.get(k) is not None]
                if gone:
                    g.lost_keys.update(gone)
                    g.worker_lost.set()
            for h, n in by_host.items():
                self._hosts.undrain(h, n)
                self._journal_append("undrain", host=h, slots=n)
            # un-burn the requests: the world can BECOME viable
            # (discovery adds a host) before the doomed worker dies,
            # and a drain watcher is latched after its one publish —
            # without the retry the advance notice would be permanently
            # lost.  Backoff bounds the replanning churn.
            for t in tokens:
                g.handled_tokens.discard(t)
                delay = min(
                    g.deferred_tokens.get(t, (0.0, 1.0))[1] * 2, 30.0)
                g.deferred_tokens[t] = (time.monotonic() + delay, delay)
            get_logger().warning(
                "no viable planned world for %s %s; retrying with "
                "backoff, reactive recovery covers an actual death",
                event_kind, notice_meta)
            return "retry"
        # the tokens are journaled only now that their planned world is
        # COMMITTED (journal + publish): journaling them earlier would
        # let a takeover believe a notice was handled when no world was
        # ever published for it — the worker would then die reactively,
        # which is exactly the fallback the reactive path covers
        for t in tokens:
            self._journal_token(t)
        # rebind the coordinator BEFORE spawning: run_slot reads the
        # runtime's coord fields at call time, and a replacement
        # pointed at the dead world's port would never find the mesh
        new_slots2, rec_gen, replacements, g.coord_addr, \
            g.coord_port = recovered
        for s in replacements:
            g.spawn(s, rec_gen)
        g.essential_keys = survivors + [
            (rec_gen, s.rank) for s in replacements]
        g.essential_gen = g.world_gen = g.numbering_gen = rec_gen
        g.slots = new_slots2
        g.np = len(new_slots2)
        return "planned"

    def _poll_drain_notices(self, g: _GenRuntime) -> bool:
        doomed, notice_meta, tokens = self._scan_drain_notices(g)
        if not doomed:
            return False
        return self._plan_world_out(g, doomed, notice_meta, tokens,
                                    drain_cooldown_s(),
                                    "drain_notice_handled")

    def _poll_action_requests(self, g: _GenRuntime) -> bool:
        groups = self._scan_action_requests(g)
        for kind, reserve_full in _ACTION_KINDS.items():
            doomed, meta, tokens = groups[kind]
            if not doomed:
                continue
            cooldown = drain_cooldown_s() if reserve_full \
                else restart_cooldown_s()
            result = self._plan_world_out(g, doomed, meta, tokens,
                                          cooldown,
                                          "autopilot_action_handled")
            if not result:
                continue  # deferred: try the other action kinds
            if kind == "quarantine" and result == "planned":
                # ISSUE 13: unlike a preemption drain, a quarantine IS
                # evidence against the hardware — blocklist the
                # divergent rank's host, with the canary digests that
                # convicted it on the record (re-admitted only by the
                # HVD_TPU_BLOCKLIST_COOLDOWN_S expiry)
                from horovod_tpu.diagnostics.flight_recorder import (
                    record_event)
                for m in meta:
                    ev = {"reason": "quarantine", "rank": m["rank"],
                          "policy": m.get("policy"),
                          "evidence": m.get("evidence")}
                    self._hosts.blacklist(m["host"], evidence=ev)
                    self._journal_append("blocklist", host=m["host"],
                                         evidence=ev,
                                         ts=journal_mod.now_wall())
                    record_event("quarantine_blocklisted",
                                 host=m["host"], rank=m["rank"],
                                 policy=m.get("policy"),
                                 evidence=m.get("evidence"))
                    get_logger().error(
                        "quarantine: host %s (rank %d) blocklisted for "
                        "replica divergence — policy %s, evidence %s",
                        m["host"], m["rank"], m.get("policy"),
                        m.get("evidence"))
            return True
        return False

    def _recover_lost_workers(self, g: _GenRuntime) -> None:
        """A worker crashed mid-generation: recover the world in place
        (or set the failure flag for the generation-restart backstop).
        Lets a correlated burst finish dying before planning: the other
        ranks of a doomed host group are typically milliseconds behind
        the first exit, and one settled re-mesh beats a cascade of
        partial ones."""
        time.sleep(loss_settle_s())
        with g.fail_lock:
            g.worker_lost.clear()
            lost_now = set(g.lost_keys)
            blamed = lost_now & g.originators
            # this round handles exactly lost_now; clearing lets the
            # NEXT crash classify as an originator again and keeps
            # host_crashes from re-counting old losses (originators
            # pruned alongside: keys are per-instance, a handled one
            # can never recur)
            g.lost_keys.clear()
            g.originators -= lost_now
            survivors = [k for k in g.essential_keys
                         if k not in lost_now]
        # only the originating FAILURE charges its host's crash budget;
        # casualties are fallout, not evidence the host is bad (their
        # replacement still respawns below)
        for k in blamed:
            h = g.slot_by_key[k].hostname
            g.host_crashes[h] = g.host_crashes.get(h, 0) + 1
        recovered = self._try_inplace_recovery(
            survivors, g.results, g.threads, g.slot_by_key,
            g.current_rank, g.np, g.host_crashes, gen_runtime=g)
        if recovered is None:
            g.failure.set()  # not viable: generation-restart path
            return
        # rebind the coordinator BEFORE spawning (see _plan_world_out)
        new_slots2, rec_gen, replacements, g.coord_addr, \
            g.coord_port = recovered
        for s in replacements:
            g.spawn(s, rec_gen)
        g.essential_keys = survivors + [
            (rec_gen, s.rank) for s in replacements]
        g.essential_gen = g.world_gen = g.numbering_gen = rec_gen
        g.slots = new_slots2
        g.np = len(new_slots2)

    def _apply_membership_change(self, g: _GenRuntime) -> None:
        """Discovery changed the host set mid-generation: shrink in
        place (capacity loss), grow in place (new slots spawned into
        the RUNNING generation), or set the teardown flag for a
        generation restart when neither is safe."""
        new_hosts = self._hosts.current_hosts()
        new_np = self._cap_np()
        old_hostnames = {s.hostname for s in g.slots}
        still_there = old_hostnames.issubset(
            {h.hostname for h in new_hosts})
        if not still_there or new_np < g.np:
            # capacity loss: keep the remaining workers IN PLACE when
            # they can all apply a world doc (elastic state committed
            # at least once); dropped workers exit via the
            # not-in-new-world path at their next commit. Anything
            # else — a finished essential, unregistered workers, too
            # little capacity — takes the generation-restart path.
            if any(g.results.get(k) is not None
                   for k in g.essential_keys):
                g.teardown.set()
                return
            # keep workers per host up to that host's NEW slot count
            # (the downscaled host must actually lose workers) in
            # current-rank order, capped at the new world size
            new_caps = {h.hostname: h.slots for h in new_hosts}
            alive = [k for k in g.essential_keys
                     if g.threads[k].is_alive()]
            kept, used = [], {}
            for k in sorted(alive, key=lambda k: g.current_rank[k]):
                h = g.slot_by_key[k].hostname
                if len(kept) < new_np and \
                        used.get(h, 0) < new_caps.get(h, 0):
                    kept.append(k)
                    used[h] = used.get(h, 0) + 1
            dropped = [k for k in g.essential_keys if k not in kept]
            with g.fail_lock:
                # BEFORE the publish: a dropped worker can read the
                # pushed doc and exit before this loop resumes, and
                # that exit must not be classified as a crash
                g.expected_exits.update(dropped)
            recovered = self._try_inplace_recovery(
                kept, g.results, g.threads, g.slot_by_key,
                g.current_rank, new_np, g.host_crashes,
                charge_reset=False, gen_runtime=g)
            if recovered is None:
                g.teardown.set()
                return
            new_slots2, rec_gen, replacements, g.coord_addr, \
                g.coord_port = recovered
            for s in replacements:
                g.spawn(s, rec_gen)
            g.essential_keys = kept + [(rec_gen, s.rank)
                                       for s in replacements]
            g.essential_gen = g.world_gen = g.numbering_gen = rec_gen
            g.slots = new_slots2
            g.np = len(new_slots2)
            return
        if new_np <= g.np:
            return  # capacity we are not using anyway
        # GROWTH: stable assignment keeps existing ranks; spawn only
        # the new slots, publish the new world for survivor resync
        new_slots = get_host_assignments(new_hosts, new_np)
        if not all(ns.rank == s.rank and ns.hostname == s.hostname
                   for ns, s in zip(new_slots, g.slots)):
            # assignment reshuffled existing ranks (host reordering):
            # in-place resync would double-assign ranks — restart
            get_logger().warning(
                "growth reshuffled existing ranks; falling back to a "
                "generation restart")
            g.teardown.set()
            return
        g.coord_port = free_port()  # fresh rendezvous for the new world
        gen = self._generation
        self._generation += 1
        get_logger().info(
            "elastic generation %d (growth, in-place): np=%d->%d",
            gen, g.np, new_np)
        # growth keeps the numbering: the runtime's current_rank simply
        # extends with the about-to-be-spawned slots' keys
        cr = dict(g.current_rank)
        cr.update({(gen, s.rank): s.rank for s in new_slots[g.np:]})
        with g.fail_lock:
            exp = set(g.expected_exits)
            drn = set(g.drained_exits)
        self._publish_world(gen, new_slots, g.coord_addr, g.coord_port,
                            runtime=self._runtime_record(
                                gen, new_slots, g.coord_addr,
                                g.coord_port, g.essential_keys, cr,
                                g.numbering_gen, g.essential_gen,
                                exp, drn))
        g.world_gen = gen  # survivors adopt this gen; notices carry it
        for s in new_slots[g.np:]:
            g.spawn(s, gen)
        g.slots = new_slots
        g.np = new_np

    # -- one generation ------------------------------------------------------
    def _run_generation(self) -> str:
        """Launch workers for the current host set; returns SUCCESS /
        FAILURE / 'HOSTS_CHANGED'. Growth extends the RUNNING generation
        (new world published to the KV, survivors resync at commit);
        shrink/failure tears it down for a restart."""
        hosts = self._hosts.current_hosts()
        np = self._cap_np()
        slots = get_host_assignments(hosts, np)
        coord_port = free_port()
        coord_addr = "127.0.0.1" if slots[0].hostname in (
            "localhost", "127.0.0.1") else slots[0].hostname
        self._registry.reset(np)
        self._journal_append("reset", count=self._registry.reset_count)
        # drop listener registrations from the previous generation: its
        # processes are gone, and pushing signed world docs at dead (or
        # recycled) host:port addresses wastes a thread per publish and
        # could hand the doc to an unrelated process. This generation's
        # workers re-register at their first commit.
        self._kv.clear("notify")
        self._journaled_notify.clear()
        # stale drain notices die with their generation too: the rank a
        # notice names is only meaningful in the world that published it,
        # and the doomed HOST is already held out by its HostManager
        # drain reservation regardless
        self._kv.clear("drain")
        # autopilot action requests die with their generation too: the
        # rank a request targets is only meaningful in the world whose
        # finding fired it
        self._kv.clear("action")
        # completion receipts are per-generation too (rank + generation
        # stamped): a stale one must not vouch for this world's workers
        self._kv.clear("result")
        self._hosts_changed.clear()
        gen = self._generation
        self._generation += 1
        get_logger().info("elastic generation %d: np=%d hosts=%s", gen, np,
                          [h.hostname for h in hosts])
        self._publish_world(gen, slots, coord_addr, coord_port,
                            runtime=self._runtime_record(
                                gen, slots, coord_addr, coord_port,
                                [(gen, s.rank) for s in slots],
                                {(gen, s.rank): s.rank for s in slots},
                                gen, gen))

        g = _GenRuntime(slots, gen, coord_addr, coord_port)
        g.spawn = lambda slot, slot_gen: self._spawn_worker(
            g, slot, slot_gen)
        for s in slots:
            g.spawn(s, gen)
        return self._monitor_generation(g)

    def _run_slot(self, g: _GenRuntime, slot, slot_gen: int) -> None:
        key = (slot_gen, slot.rank)
        extra_env = {
            "HVD_TPU_ELASTIC": "1",
            "HVD_ELASTIC_GENERATION": str(slot_gen),
            "HVD_ELASTIC_CKPT": self._ckpt_dir,
            "HVD_ELASTIC_SECRET": self._world_secret.hex(),
            "HVD_ELASTIC_KV": f"127.0.0.1:{self._kv.port}"
            if slot.hostname in ("localhost", "127.0.0.1")
            else f"{self._driver_addr}:{self._kv.port}"}
        prefix = f"[{slot.rank}]" if self._verbose else ""

        def note_pid(pid):
            # the journaled pid is what lets a takeover driver ADOPT
            # this worker: monitor its liveness, and kill its process
            # group if the generation must die
            self._journal_append("spawn", key=list(key),
                                 host=slot.hostname, rank=slot.rank,
                                 pid=pid, ts=journal_mod.now_wall())

        if self._remote_exec is not None:
            # agent transport: ship the RAW worker command + env; the
            # agent on slot.hostname execs it locally (no ssh wrap).
            # The remote pid is unknowable here — journaled as None, so
            # a takeover waits on the worker's completion receipt
            # instead of a liveness probe (documented limitation).
            note_pid(None)
            from horovod_tpu.runner.exec_run import build_worker_env
            wenv = build_worker_env(slot, g.coord_addr, g.coord_port,
                                    self._env)
            wenv.update(extra_env)
            if self._preshared_secret is not None:
                # the caller distributed the secret over its own
                # trusted channel; keep it off the wire
                wenv.pop("HVD_ELASTIC_SECRET", None)
            rc = self._remote_exec(slot, self._command, wenv,
                                   [g.failure, g.teardown])
        else:
            # local-vs-ssh dispatch shared with the static launcher so
            # multi-host elastic jobs actually place workers remotely
            cmd, env = slot_command(
                slot, self._command, g.coord_addr, g.coord_port,
                self._env, extra_env=extra_env)
            rc = safe_execute(cmd, env=env, prefix=prefix,
                              events=[g.failure, g.teardown],
                              timestamp=self._timestamp_output,
                              on_start=note_pid)
        self._classify_exit(g, slot, key, rc)

    def _classify_exit(self, g: _GenRuntime, slot, key: tuple,
                       rc: int) -> None:
        """Record one worker exit.  Distinguishes the ORIGINATING
        failure from its fallout: workers the driver tore down, and
        CASUALTIES — workers that died from the collective error the
        originator caused (a job without elastic state has no way to
        ride out a peer loss).  Only the originator counts as FAILURE,
        so the blacklist and the restart decision see one crash, not a
        cascade.  A crash does not fail the generation outright: the
        monitor loop first tries to recover the world in place."""
        if rc == 0:
            g.results[key] = SUCCESS
            self._registry.record(slot.rank, slot.hostname, SUCCESS)
            self._journal_append("exit", key=list(key), state=SUCCESS,
                                 rank=slot.rank, host=slot.hostname)
            return
        with g.fail_lock:
            torn_down = g.failure.is_set() or g.teardown.is_set()
            expected = key in g.expected_exits
            casualty = bool(g.lost_keys) and not torn_down \
                and not expected
            if not torn_down and not expected:
                g.lost_keys.add(key)
                if not casualty:
                    g.originators.add(key)
                g.worker_lost.set()
            # classification is atomic with the membership checks:
            # _plan_world_out's no-viable-world revert edits these
            # sets under the same lock and must observe either a
            # fully recorded exit or none at all
            if key in g.drained_exits:
                state = DRAINED
            elif torn_down or casualty or expected:
                state = TERMINATED
            else:
                state = FAILURE
            g.results[key] = state
        self._registry.record(slot.rank, slot.hostname, state)
        self._journal_append("exit", key=list(key), state=state,
                             rank=slot.rank, host=slot.hostname)

    def _spawn_worker(self, g: _GenRuntime, slot, slot_gen: int) -> None:
        key = (slot_gen, slot.rank)
        t = threading.Thread(target=self._run_slot,
                             args=(g, slot, slot_gen), daemon=True)
        g.threads[key] = t
        g.slot_by_key[key] = slot
        g.current_rank[key] = slot.rank
        t.start()

    def _monitor_generation(self, g: _GenRuntime) -> str:
        """The generation's poll loop + final classification — split
        from :meth:`_run_generation` so a takeover driver can resume
        monitoring a REBUILT generation without re-spawning it."""
        while any(t.is_alive() for t in g.threads.values()):
            time.sleep(0.25)
            self._poll_tick += 1
            if self._chaos is not None:
                # the `driver` chaos seam: one invocation per poll tick
                # (kill/exit end this process mid-decision — the
                # supervisor respawns into a journal takeover; stall
                # freezes the control plane while workers ride it out)
                self._chaos.fire("driver", index=self._poll_tick)
            # WAL the listener registrations this tick observes: a
            # takeover driver restores them, because a survivor that
            # never noticed the outage will never re-register on its own
            self._journal_notify_observations()
            if not g.failure.is_set() and not g.teardown.is_set() and \
                    all(g.results.get(k) == SUCCESS
                        for k in g.essential_keys):
                # survivors finished; kill growth stragglers still waiting
                # for a rendezvous that will never complete
                g.teardown.set()
            # -- a worker crashed: recover the world in place --------------
            if g.worker_lost.is_set() and not g.failure.is_set() and \
                    not g.teardown.is_set():
                if self._adoption_settling(g):
                    continue  # survivors still re-registering (takeover)
                self._recover_lost_workers(g)
                continue
            if not g.failure.is_set() and not g.teardown.is_set():
                # -- a preemption/maintenance drain notice arrived ---------
                if self._poll_drain_notices(g):
                    continue
                # -- an autopilot action request arrived (ISSUE 12) --------
                if self._poll_action_requests(g):
                    continue
            if g.failure.is_set() or not self._hosts_changed.is_set():
                continue
            # -- membership changed mid-generation -------------------------
            self._hosts_changed.clear()
            self._apply_membership_change(g)

        ess_ok = all(
            g.results.get(k) == SUCCESS for k in g.essential_keys)
        if ess_ok:
            # only the ESSENTIAL workers are guaranteed complete —
            # in-place growth may have raised np while its stragglers
            # were torn down after the survivors finished in the old
            # world, and crash-shrunken workers' FAILURE records were
            # absorbed by the in-place re-mesh
            self._final_np = len(g.essential_keys)
            self._final_gen = g.essential_gen
            return SUCCESS
        if (g.teardown.is_set() or self._hosts_changed.is_set()) and \
                self._registry.count(FAILURE) == 0:
            return "HOSTS_CHANGED"
        if self._registry.count(FAILURE) > 0:
            for host, n in self._registry.failed_hosts().items():
                # a host whose every worker failed is blacklisted
                # (reference: driver blacklist, driver.py:297-313)
                host_slots = sum(1 for s in g.slots
                                 if s.hostname == host)
                if n >= host_slots:
                    ev = {"reason": "all_workers_failed", "failures": n,
                          "slots": host_slots}
                    self._hosts.blacklist(host, evidence=ev)
                    self._journal_append("blocklist", host=host,
                                         evidence=ev,
                                         ts=journal_mod.now_wall())
            return FAILURE
        self._final_np = len(g.essential_keys)
        self._final_gen = g.essential_gen
        return SUCCESS

    def _adoption_settling(self, g: _GenRuntime) -> bool:
        """True while a freshly adopted generation should HOLD OFF
        recovery planning: right after a takeover no survivor has
        re-registered its elastic listener yet (the old driver's
        ``notify`` scope died with it), so planning now would flunk the
        viability check and burn the generation restart the takeover
        exists to avoid.  Clears as soon as every live survivor has
        re-registered, or when the settle deadline passes (a survivor
        that never re-registers really is unrecoverable in place)."""
        deadline = getattr(g, "adopted_until", None)
        if deadline is None or time.monotonic() >= deadline:
            return False
        notify = {str(r) for r in self._kv.scope("notify")}
        with g.fail_lock:
            lost = set(g.lost_keys)
        waiting = [k for k in g.essential_keys
                   if k not in lost and g.results.get(k) is None
                   and g.threads[k].is_alive()
                   and str(g.current_rank.get(k)) not in notify]
        return bool(waiting)

    # -- crash takeover (docs/ELASTIC.md "Driver failover & takeover") -------
    def _begin_takeover(self) -> _GenRuntime:
        """Become the driver the journal describes: restore exclusion
        state and the reset budget, re-publish the last committed world
        doc VERBATIM (its HMAC is over the sort_keys canonical form, so
        the old signature stays valid), and rebuild the running
        generation from spawn/exit records — workers mid-step never
        re-mesh; they just find the same world at their next poll."""
        import json as _json
        state = self._replay
        assert state is not None and state.world is not None
        self._journal_append("takeover", critical=True, pid=os.getpid(),
                             ts=journal_mod.now_wall())
        try:
            from horovod_tpu.metrics.registry import default_registry
            default_registry().counter(
                "hvd_driver_takeovers_total",
                help="elastic driver crash takeovers completed from the "
                     "control-plane journal").inc()
        except Exception:
            pass
        # the takeover span continues the adopted generation's trace —
        # one trace id from the world that was published through the
        # crash and into the recovered control plane
        from horovod_tpu import tracing
        doc = state.world["doc"]
        ctx = tracing.decode(doc.get("traceparent")) \
            or tracing.new_trace("elastic")
        try:
            from horovod_tpu.diagnostics.flight_recorder import \
                record_event
            record_event("driver_takeover", pid=os.getpid(),
                         generation=state.world_gen,
                         np=int(state.world.get("np", 0)),
                         adopted=len(state.live_workers()),
                         replayed_exits=len(state.exits),
                         blocklisted=len(state.blocklist),
                         **tracing.fields(ctx))
        except Exception:
            pass
        tracing.record_span("elastic", "driver_takeover",
                            tracing.child(ctx, "elastic"),
                            generation=state.world_gen,
                            adopted=len(state.live_workers()))
        self._hosts.restore_state(state.blocklist, state.drains)
        self._registry.restore_reset_count(state.reset_count)
        # seed the discovery view BEFORE clearing the change flag: the
        # takeover must not misread "first refresh populated an empty
        # view" as a mid-generation membership change
        try:
            self._hosts.update_available_hosts()
        except Exception as e:
            get_logger().warning(
                "takeover: initial host discovery failed (%s); the "
                "discovery loop will retry", e)
        self._hosts_changed.clear()
        # restore the journaled listener registrations: a survivor whose
        # KV gets retried straight through the outage never notices the
        # driver changed and never re-registers — without this restore
        # the empty ``notify`` scope flunks the in-place recovery
        # viability check and burns a generation restart
        for rank, rec in state.notify.items():
            addr = rec.get("addr", "")
            if addr:
                self._kv.put("notify", rank, addr.encode())
                self._journaled_notify[rank] = addr
        self._kv.put("world", "current", _json.dumps(doc).encode())
        g = self._rebuild_generation(state)
        get_logger().warning(
            "driver takeover complete: generation %d adopted (np=%d, "
            "%d live worker(s), %d prior exit(s), %d listener "
            "registration(s) restored, %d blocklisted host(s), reset "
            "budget %d spent)", g.world_gen, g.np,
            sum(1 for t in g.threads.values() if t.is_alive()),
            len(state.exits), len(state.notify), len(state.blocklist),
            state.reset_count)
        return g

    def _rebuild_generation(self,
                            state: journal_mod.ReplayState) -> _GenRuntime:
        """A live :class:`_GenRuntime` from the journal's last
        ``world_publish`` runtime + the spawn/exit records after it."""
        w = state.world
        slots = [SlotInfo(**d) for d in w["slots"]]
        slot_by_rank = {s.rank: s for s in slots}
        g = _GenRuntime(slots, int(w["essential_gen"]),
                        w["coord_addr"], int(w["coord_port"]))
        g.world_gen = int(w["world_gen"])
        g.numbering_gen = int(w["numbering_gen"])
        g.essential_keys = [tuple(k) for k in w["essential_keys"]]
        g.current_rank = {tuple(k): r for k, r in w["current_rank"]}
        g.expected_exits = {tuple(k)
                            for k in w.get("expected_exits", [])}
        g.drained_exits = {tuple(k) for k in w.get("drained_exits", [])}
        # token payloads journal as utf-8 text; the live dedupe set
        # holds the KV's raw BYTES — re-encode or every replayed token
        # would silently fail to match and be re-handled
        g.handled_tokens = {(s, k, r.encode("utf-8"))
                            for (s, k, r) in state.tokens}
        g.spawn = lambda slot, slot_gen: self._spawn_worker(
            g, slot, slot_gen)
        g.adopted_until = time.monotonic() + takeover_settle_s()

        def slot_for(key, rec):
            rank = g.current_rank.get(key)
            if rank in slot_by_rank:
                return slot_by_rank[rank]
            # spawn record as fallback (a straggler whose publish-time
            # rank is gone): enough identity to classify, not to place
            return SlotInfo(hostname=rec.get("host", "localhost"),
                            rank=key[1], local_rank=0, cross_rank=0,
                            size=len(slots), local_size=1,
                            cross_size=1)

        # exits the dead driver already classified: preloaded as
        # finished bookkeeping so membership checks (threads[k]
        # .is_alive() with no KeyError guard) and the success test see
        # them.  Only the current numbering window counts — older exits
        # were absorbed by re-meshes the journal already published.
        lo, hi = g.numbering_gen, g.world_gen
        lost_essentials = []
        for key_t, rec in state.exits.items():
            key = tuple(key_t)
            if not lo <= key[0] <= hi:
                continue
            st = rec.get("state", FAILURE)
            slot = slot_for(key, rec)
            g.results[key] = st
            g.threads[key] = _finished_thread()
            g.slot_by_key.setdefault(key, slot)
            g.current_rank.setdefault(key, rec.get("rank", key[1]))
            self._registry.record(rec.get("rank", key[1]),
                                  rec.get("host", slot.hostname), st)
            if st == FAILURE and key in g.essential_keys:
                lost_essentials.append(key)
        # live workers: adopt.  A local pid gets a liveness monitor
        # (and, if the generation must die, a process-group kill — the
        # setsid spawn is why these workers outlived their driver); a
        # remote/pid-less worker can only be awaited via its signed
        # completion receipt.
        import socket as _socket
        local_names = {"localhost", "127.0.0.1", _socket.gethostname()}
        to_start = []
        for key_t, rec in state.live_workers().items():
            key = tuple(key_t)
            if key in g.results:
                continue
            slot = slot_for(key, rec)
            g.slot_by_key.setdefault(key, slot)
            g.current_rank.setdefault(key, rec.get("rank", key[1]))
            pid = rec.get("pid")
            if pid and slot.hostname in local_names:
                t = threading.Thread(
                    target=self._monitor_adopted,
                    args=(g, key, slot, int(pid)), daemon=True)
            else:
                t = threading.Thread(
                    target=self._await_adopted_result,
                    args=(g, key, slot), daemon=True)
            g.threads[key] = t
            to_start.append(t)
        # essential keys with NEITHER an exit nor a spawn record (a
        # lost journal append, or a spawn the crash preempted): treated
        # as lost, which routes them through the normal in-place
        # recovery once the survivors have re-registered
        for key in list(g.essential_keys):
            if key in g.threads:
                continue
            slot = slot_for(key, {})
            g.slot_by_key.setdefault(key, slot)
            g.threads[key] = _finished_thread()
            get_logger().warning(
                "takeover: essential worker %s has no journal record; "
                "classifying it lost", key)
            self._classify_exit(g, slot, key, 1)
        # exits the dead driver classified FAILURE but never finished
        # recovering (crashed mid-re-mesh — the worst case): re-mark
        # them lost so the monitor loop plans the recovery the old
        # driver never published
        if lost_essentials:
            with g.fail_lock:
                g.lost_keys.update(lost_essentials)
                g.originators.update(lost_essentials)
                g.worker_lost.set()
        for t in to_start:
            t.start()
        return g

    def _monitor_adopted(self, g: _GenRuntime, key: tuple, slot,
                         pid: int) -> None:
        """Stand-in for the :meth:`_run_slot` thread of a worker THIS
        process never spawned: poll the adopted pid for liveness,
        escalate a generation teardown to its process group, and
        classify the exit from the worker's signed completion receipt
        (the exit CODE died with the old driver)."""
        import signal as _signal
        killed_at = None
        while True:
            if g.failure.is_set() or g.teardown.is_set():
                try:
                    pgid = os.getpgid(pid)
                    if killed_at is None:
                        os.killpg(pgid, _signal.SIGTERM)
                        killed_at = time.monotonic()
                    elif time.monotonic() - killed_at > \
                            GRACEFUL_TERMINATION_TIME_S:
                        os.killpg(pgid, _signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                break
            except PermissionError:
                pass  # alive, different uid — keep watching
            time.sleep(0.25)
        rc = 0 if self._adopted_result_ok(g, key) else 1
        self._classify_exit(g, slot, key, rc)

    def _await_adopted_result(self, g: _GenRuntime, key: tuple,
                              slot) -> None:
        """Adoption monitor for a worker with no observable pid (remote
        exec, or the spawn record lost its pid): the only signal is the
        signed completion receipt.  Documented limitation: such a
        worker's DEATH is invisible until a peer's transport error
        surfaces it — the reactive path still covers it, later."""
        while not (g.failure.is_set() or g.teardown.is_set()):
            if self._adopted_result_ok(g, key):
                self._classify_exit(g, slot, key, 0)
                return
            time.sleep(1.0)
        self._classify_exit(g, slot, key, 1)

    def _adopted_result_ok(self, g: _GenRuntime, key: tuple) -> bool:
        """True when the KV ``result`` scope holds a VALID completion
        receipt for the worker: HMAC-signed with the world secret
        (receipts influence SUCCESS classification and the PUT surface
        is open to the network), rank matching, generation inside the
        current numbering window."""
        import hmac as _hmac
        import json as _json
        rank = g.current_rank.get(key)
        if rank is None:
            return False
        raw = self._kv.get("result", str(rank))
        if raw is None:
            return False
        try:
            doc = _json.loads(raw)
            if not isinstance(doc, dict):
                return False
            from horovod_tpu.elastic import world_doc_signature
            sig = doc.get("sig")
            if not isinstance(sig, str) or not _hmac.compare_digest(
                    sig, world_doc_signature(self._world_secret, doc)):
                return False
            if int(doc.get("rank", -1)) != int(rank):
                return False
            return g.numbering_gen <= \
                int(doc.get("generation", -1)) <= g.world_gen
        except (ValueError, TypeError):
            return False

    @property
    def final_np(self) -> Optional[int]:
        """World size of the generation that completed successfully (None
        until then) — callers collecting per-rank artifacts use it to
        ignore leftovers from aborted generations."""
        return getattr(self, "_final_np", None)

    @property
    def final_generation(self) -> Optional[int]:
        """Generation number the completed ranks were launched with
        (their ``HVD_ELASTIC_GENERATION``) — pairs with final_np for
        generation-scoped artifact collection."""
        return getattr(self, "_final_gen", None)

    # -- main loop -----------------------------------------------------------
    def run(self) -> int:
        adopted: Optional[_GenRuntime] = None
        if self._takeover:
            # the fleet is (presumably) still running: adopt it instead
            # of waiting for min hosts to relaunch it
            adopted = self._begin_takeover()
        else:
            self._wait_for_min_hosts(timeout=self._start_timeout)
        disc = threading.Thread(target=self._discovery_loop, daemon=True)
        disc.start()
        try:
            while True:
                if adopted is not None:
                    g, adopted = adopted, None
                    result = self._monitor_generation(g)
                else:
                    result = self._run_generation()
                if result == SUCCESS:
                    # clean_exit tells a later takeover attempt (and the
                    # supervisor) this rc was ON PURPOSE, not a crash
                    self._journal_append("clean_exit", rc=0)
                    return 0
                if self._registry.reset_limit_reached():
                    get_logger().error(
                        "elastic reset limit reached after %d generations",
                        self._registry.reset_count)
                    self._journal_append("clean_exit", rc=1)
                    return 1
                # wait until we have enough usable slots again
                try:
                    self._wait_for_min_hosts(timeout=self._elastic_timeout)
                except TimeoutError:
                    self._journal_append("clean_exit", rc=1)
                    return 1
        finally:
            self._stop.set()
            disc.join(timeout=3)
            self._kv.stop()
            if self._journal is not None:
                self._journal.close()


def run_elastic(discovery: HostDiscovery, np: Optional[int],
                command: List[str],
                min_np: int = 1, max_np: Optional[int] = None,
                env: Optional[Dict[str, str]] = None,
                verbose: bool = False,
                reset_limit: Optional[int] = None,
                timestamp_output: bool = False,
                start_timeout: Optional[float] = None,
                elastic_timeout: Optional[float] = None,
                journal_dir: Optional[str] = None,
                takeover: bool = False) -> int:
    driver = ElasticDriver(discovery, command, min_np=min_np, max_np=max_np,
                           env=env, verbose=verbose, reset_limit=reset_limit,
                           target_np=np, timestamp_output=timestamp_output,
                           start_timeout=start_timeout,
                           elastic_timeout=elastic_timeout,
                           journal_dir=journal_dir, takeover=takeover)
    return driver.run()
