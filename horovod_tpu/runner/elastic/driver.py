"""Elastic driver: discovery-driven launch/relaunch with blacklist and
rank-stable assignments.

Reference: ``horovod/runner/elastic/driver.py`` (``ElasticDriver``: discovery
thread :181-201, stable rank assignment :233-275, worker spawn per slot
:277-295, blacklist + exit handling :297-313).

TPU-native design difference: the reference hot-resyncs surviving worker
processes (NCCL communicators can be rebuilt in place). On TPU the XLA
runtime and meshes must be re-created on world change anyway, so elasticity
is **process-restart based**: on membership change or worker failure the
driver terminates the generation, recomputes assignments (stable ranks,
failed hosts blacklisted), and relaunches; workers resume from their last
committed :class:`horovod_tpu.elastic.State` checkpoint (epoch passed via
``HVD_ELASTIC_EPOCH``/``HVD_ELASTIC_CKPT``).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from horovod_tpu.common.logging import get_logger
from horovod_tpu.runner.elastic.discovery import HostDiscovery, HostManager
from horovod_tpu.runner.elastic.registration import (FAILURE, SUCCESS,
                                                     TERMINATED,
                                                     WorkerStateRegistry)
from horovod_tpu.runner.exec_run import (free_port, slot_command)
from horovod_tpu.runner.hosts import get_host_assignments
from horovod_tpu.runner.safe_exec import safe_execute

DISCOVERY_INTERVAL_S = 1.0


class ElasticDriver:
    def __init__(self, discovery: HostDiscovery, command: List[str],
                 min_np: int = 1, max_np: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None,
                 reset_limit: Optional[int] = None,
                 verbose: bool = False,
                 ckpt_dir: Optional[str] = None,
                 target_np: Optional[int] = None) -> None:
        self._hosts = HostManager(discovery)
        self._command = command
        self._min_np = min_np
        self._max_np = max_np
        self._target_np = target_np
        self._env = dict(env if env is not None else os.environ)
        self._registry = WorkerStateRegistry(reset_limit)
        self._verbose = verbose
        self._ckpt_dir = ckpt_dir or tempfile.mkdtemp(prefix="hvd_elastic_")
        self._stop = threading.Event()
        self._hosts_changed = threading.Event()
        self._generation = 0

    # -- discovery thread (reference: driver.py:181-201) --------------------
    def _discovery_loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self._hosts.update_available_hosts():
                    self._hosts_changed.set()
            except Exception as e:  # discovery script hiccup: keep going
                get_logger().warning("host discovery failed: %s", e)
            time.sleep(DISCOVERY_INTERVAL_S)

    def _wait_for_min_hosts(self, timeout: float = 600.0) -> None:
        deadline = time.time() + timeout
        consecutive_failures = 0
        while time.time() < deadline:
            try:
                self._hosts.update_available_hosts()
                consecutive_failures = 0
            except Exception as e:  # transient discovery hiccup: keep going
                consecutive_failures += 1
                get_logger().warning("host discovery failed: %s", e)
                if consecutive_failures >= 5:
                    # permanent misconfiguration (bad script path etc.):
                    # surface the real error instead of spinning to timeout
                    raise RuntimeError(
                        "host discovery failed 5 times in a row; check the "
                        f"discovery script: {e}") from e
            if self._hosts.slot_count() >= self._min_np:
                return
            time.sleep(DISCOVERY_INTERVAL_S)
        raise TimeoutError(
            f"needed {self._min_np} slots, found {self._hosts.slot_count()}")

    # -- one generation ------------------------------------------------------
    def _run_generation(self) -> str:
        """Launch workers for the current host set; returns SUCCESS /
        FAILURE / 'HOSTS_CHANGED'."""
        hosts = self._hosts.current_hosts()
        np = min(self._target_np or self._hosts.slot_count(),
                 self._max_np or self._hosts.slot_count(),
                 self._hosts.slot_count())
        slots = get_host_assignments(hosts, np)
        coord_port = free_port()
        coord_addr = "127.0.0.1" if slots[0].hostname in (
            "localhost", "127.0.0.1") else slots[0].hostname
        self._registry.reset(np)
        self._hosts_changed.clear()
        gen = self._generation
        self._generation += 1
        get_logger().info("elastic generation %d: np=%d hosts=%s", gen, np,
                          [h.hostname for h in hosts])

        failure = threading.Event()
        fail_lock = threading.Lock()

        def run_slot(slot):
            # local-vs-ssh dispatch shared with the static launcher so
            # multi-host elastic jobs actually place workers remotely
            cmd, env = slot_command(
                slot, self._command, coord_addr, coord_port, self._env,
                extra_env={"HVD_TPU_ELASTIC": "1",
                           "HVD_ELASTIC_GENERATION": str(gen),
                           "HVD_ELASTIC_CKPT": self._ckpt_dir})
            prefix = f"[{slot.rank}]" if self._verbose else ""
            rc = safe_execute(cmd, env=env, prefix=prefix,
                              events=[failure, self._hosts_changed])
            if rc == 0:
                self._registry.record(slot.rank, slot.hostname, SUCCESS)
                return
            # distinguish the originating failure from workers the driver
            # tore down because of it (those must not poison the blacklist)
            with fail_lock:
                torn_down = failure.is_set() or self._hosts_changed.is_set()
                failure.set()
            self._registry.record(slot.rank, slot.hostname,
                                  TERMINATED if torn_down else FAILURE)

        threads = [threading.Thread(target=run_slot, args=(s,), daemon=True)
                   for s in slots]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        if self._registry.count(SUCCESS) == np:
            return SUCCESS
        if self._hosts_changed.is_set() and \
                self._registry.count(FAILURE) == 0:
            return "HOSTS_CHANGED"
        if self._registry.count(FAILURE) > 0:
            for host, n in self._registry.failed_hosts().items():
                # a host whose every worker failed is blacklisted
                # (reference: driver blacklist, driver.py:297-313)
                host_slots = sum(1 for s in slots if s.hostname == host)
                if n >= host_slots:
                    self._hosts.blacklist(host)
            return FAILURE
        return SUCCESS

    # -- main loop -----------------------------------------------------------
    def run(self) -> int:
        self._wait_for_min_hosts()
        disc = threading.Thread(target=self._discovery_loop, daemon=True)
        disc.start()
        try:
            while True:
                result = self._run_generation()
                if result == SUCCESS:
                    return 0
                if self._registry.reset_limit_reached():
                    get_logger().error(
                        "elastic reset limit reached after %d generations",
                        self._registry.reset_count)
                    return 1
                # wait until we have enough usable slots again
                try:
                    self._wait_for_min_hosts()
                except TimeoutError:
                    return 1
        finally:
            self._stop.set()
            disc.join(timeout=3)


def run_elastic(discovery: HostDiscovery, np: Optional[int],
                command: List[str],
                min_np: int = 1, max_np: Optional[int] = None,
                env: Optional[Dict[str, str]] = None,
                verbose: bool = False,
                reset_limit: Optional[int] = None) -> int:
    driver = ElasticDriver(discovery, command, min_np=min_np, max_np=max_np,
                           env=env, verbose=verbose, reset_limit=reset_limit,
                           target_np=np)
    return driver.run()
