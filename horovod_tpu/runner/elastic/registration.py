"""Worker state registry: counts per-slot READY/SUCCESS/FAILURE outcomes and
decides when to resume (reference: ``horovod/runner/elastic/registration.py``
``WorkerStateRegistry:28-150``)."""

from __future__ import annotations

import threading
from typing import Dict, Optional

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"
# exited nonzero because the driver tore the generation down (collateral of
# another worker's failure or a host change) — not the worker's own fault,
# so it must not count toward host blacklisting
TERMINATED = "TERMINATED"
# exited because a preemption/maintenance drain PLANNED it out of the world
# (docs/ELASTIC.md "Proactive drain & preemption") — an orderly, announced
# departure: never a FAILURE, never charged to host_crashes, never
# blocklisted, and the host is re-admitted after its drain cooldown
DRAINED = "DRAINED"


class WorkerStateRegistry:
    def __init__(self, reset_limit: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._states: Dict[int, str] = {}
        self._hosts: Dict[int, str] = {}
        self._reset_count = 0
        self._reset_limit = reset_limit

    def reset(self, size: int) -> None:
        with self._lock:
            self._states = {}
            self._hosts = {}
            self._reset_count += 1

    @property
    def reset_count(self) -> int:
        return self._reset_count

    def note_reset(self) -> None:
        """Count an IN-PLACE recovery against the same ``--reset-limit``
        budget as generation restarts: a deterministically-crashing
        worker must not respawn forever."""
        with self._lock:
            self._reset_count += 1

    def reset_limit_reached(self) -> bool:
        return (self._reset_limit is not None
                and self._reset_count > self._reset_limit)

    def restore_reset_count(self, count: int) -> None:
        """Adopt a journaled reset count (driver takeover): the
        ``--reset-limit`` budget is the JOB's, not the driver process's
        — a crash-looping worker must not get a fresh allowance every
        time the control plane restarts."""
        with self._lock:
            self._reset_count = max(self._reset_count, int(count))

    def record(self, rank: int, host: str, state: str) -> None:
        with self._lock:
            self._states[rank] = state
            self._hosts[rank] = host

    def count(self, state: str) -> int:
        with self._lock:
            return sum(1 for s in self._states.values() if s == state)

    def state_of(self, rank: int) -> Optional[str]:
        with self._lock:
            return self._states.get(rank)

    def failed_hosts(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for rank, s in self._states.items():
                if s == FAILURE:
                    h = self._hosts.get(rank, "")
                    out[h] = out.get(h, 0) + 1
            return out
