"""Static job execution: one worker process per slot, local or over ssh.

Reference: ``horovod/runner/gloo_run.py`` — rendezvous server on the driver,
slot env injection (:65-76), threaded ssh/local execs (:114-186, 226-271).
The TCP core's coordinator (rank 0) plays the Gloo rendezvous role, so the
driver only needs to pick a free port and point every worker at rank 0's
host.
"""

from __future__ import annotations

import os
import shlex
import socket
import sys
import threading
from typing import Dict, List, Optional, Tuple

from horovod_tpu.runner.hosts import HostInfo, SlotInfo, get_host_assignments
from horovod_tpu.runner.safe_exec import safe_execute

SSH_COMMAND_PREFIX = ["ssh", "-o", "StrictHostKeyChecking=no",
                      "-o", "BatchMode=yes"]

_LOCAL_NAMES = {"localhost", "127.0.0.1", socket.gethostname()}


def free_port() -> int:
    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _is_local(host: str) -> bool:
    return host in _LOCAL_NAMES


def build_worker_env(slot: SlotInfo, coord_addr: str, coord_port: int,
                     base_env: Optional[Dict[str, str]] = None
                     ) -> Dict[str, str]:
    env = dict(base_env if base_env is not None else os.environ)
    env.update(slot.to_env())
    env["HVD_TPU_COORD_ADDR"] = coord_addr
    env["HVD_TPU_COORD_PORT"] = str(coord_port)
    # reference also exports HOROVOD_GLOO_RENDEZVOUS_* (gloo_run.py:187-198)
    env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = coord_addr
    env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(coord_port)
    return env


def slot_command(slot: SlotInfo, command: List[str], coord_addr: str,
                 coord_port: int, env: Optional[Dict[str, str]] = None,
                 extra_env: Optional[Dict[str, str]] = None
                 ) -> Tuple[List[str], Dict[str, str]]:
    """Build the (argv, env) to execute for one slot: direct exec locally,
    ssh with a fully shell-quoted remote line otherwise (reference:
    ``get_remote_command``, ``gloo_run.py:114-132``)."""
    wenv = build_worker_env(slot, coord_addr, coord_port, env)
    if extra_env:
        wenv.update(extra_env)
    if _is_local(slot.hostname):
        return command, wenv
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in wenv.items()
        if k.startswith(("HOROVOD_", "HVD_TPU_", "HVD_ELASTIC_", "PATH",
                         "PYTHONPATH")))
    remote = (f"cd {shlex.quote(os.getcwd())} && env {exports} "
              + " ".join(shlex.quote(c) for c in command))
    return SSH_COMMAND_PREFIX + [slot.hostname, remote], dict(os.environ)


def launch_static(hosts: List[HostInfo], np: int, command: List[str],
                  env: Optional[Dict[str, str]] = None,
                  coord_addr: Optional[str] = None,
                  coord_port: Optional[int] = None,
                  verbose: bool = False) -> int:
    """Run ``command`` on every slot; return first nonzero exit code (or 0).

    Reference: ``launch_gloo`` (``gloo_run.py:226``): assignment → env →
    per-slot exec threads; any failure terminates the rest.
    """
    slots = get_host_assignments(hosts, np)
    coord_addr = coord_addr or (
        "127.0.0.1" if _is_local(slots[0].hostname) else slots[0].hostname)
    coord_port = coord_port or free_port()

    results: List[Optional[int]] = [None] * np
    failure = threading.Event()

    def run_slot(idx: int, slot: SlotInfo) -> None:
        cmd, run_env = slot_command(slot, command, coord_addr, coord_port,
                                    env)
        prefix = f"[{slot.rank}]<stdout/err> " if verbose else ""
        rc = safe_execute(cmd, env=run_env, prefix=prefix,
                          events=[failure])
        results[idx] = rc
        if rc != 0:
            failure.set()

    threads = [threading.Thread(target=run_slot, args=(i, s), daemon=True)
               for i, s in enumerate(slots)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for rc in results:
        if rc:
            return rc
    return 0
