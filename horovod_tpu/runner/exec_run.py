"""Static job execution: one worker process per slot, local or over ssh.

Reference: ``horovod/runner/gloo_run.py`` — rendezvous server on the driver,
slot env injection (:65-76), threaded ssh/local execs (:114-186, 226-271).
The TCP core's coordinator (rank 0) plays the Gloo rendezvous role, so the
driver only needs to pick a free port and point every worker at rank 0's
host.
"""

from __future__ import annotations

import os
import shlex
import socket
import sys
import threading
from typing import Dict, List, Optional, Tuple

from horovod_tpu.runner.hosts import HostInfo, SlotInfo, get_host_assignments
from horovod_tpu.runner.safe_exec import safe_execute

SSH_COMMAND_PREFIX = ["ssh", "-o", "StrictHostKeyChecking=no",
                      "-o", "BatchMode=yes"]

_LOCAL_NAMES = {"localhost", "127.0.0.1", socket.gethostname()}


def free_port() -> int:
    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _is_local(host: str) -> bool:
    return host in _LOCAL_NAMES


def build_worker_env(slot: SlotInfo, coord_addr: str, coord_port: int,
                     base_env: Optional[Dict[str, str]] = None
                     ) -> Dict[str, str]:
    env = dict(base_env if base_env is not None else os.environ)
    env.update(slot.to_env())
    env["HVD_TPU_COORD_ADDR"] = coord_addr
    env["HVD_TPU_COORD_PORT"] = str(coord_port)
    # reference also exports HOROVOD_GLOO_RENDEZVOUS_* (gloo_run.py:187-198)
    env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = coord_addr
    env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(coord_port)
    return env


def slot_command(slot: SlotInfo, command: List[str], coord_addr: str,
                 coord_port: int, env: Optional[Dict[str, str]] = None,
                 extra_env: Optional[Dict[str, str]] = None
                 ) -> Tuple[List[str], Dict[str, str]]:
    """Build the (argv, env) to execute for one slot: direct exec locally,
    ssh with a fully shell-quoted remote line otherwise (reference:
    ``get_remote_command``, ``gloo_run.py:114-132``)."""
    wenv = build_worker_env(slot, coord_addr, coord_port, env)
    if extra_env:
        wenv.update(extra_env)
    if _is_local(slot.hostname):
        return command, wenv
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in wenv.items()
        if k.startswith(("HOROVOD_", "HVD_TPU_", "HVD_ELASTIC_", "PATH",
                         "PYTHONPATH")))
    remote = (f"cd {shlex.quote(os.getcwd())} && env {exports} "
              + " ".join(shlex.quote(c) for c in command))
    return SSH_COMMAND_PREFIX + [slot.hostname, remote], dict(os.environ)


def probe_coordinator_address(hostnames: List[str],
                              restrict: Optional[List[str]] = None,
                              verbose: bool = False) -> Optional[str]:
    """Multi-NIC bootstrap (reference: ``get_common_interfaces``,
    ``driver/driver_service.py:49-235``): start a task service on every
    distinct host, ring-probe candidate interfaces, return a rendezvous
    address every worker can reach. None = all-local, no probing needed."""
    import secrets as _secrets
    import subprocess

    distinct: List[str] = []
    for h in hostnames:
        if h not in distinct:
            distinct.append(h)
    if all(_is_local(h) for h in distinct):
        return None

    from horovod_tpu.runner.service import (TaskClient, TaskService,
                                            find_routable_interfaces,
                                            pick_rendezvous_address)
    secret = _secrets.token_bytes(16)
    services: List[TaskService] = []
    procs: List[subprocess.Popen] = []
    clients_by_idx: Dict[int, TaskClient] = {}
    try:
        # spawn everything first (concurrent ssh session setup), collect
        # ports in parallel with a read deadline — a wedged remote must
        # not hang the launch (probing is best-effort bootstrap)
        pending: List[Tuple[int, str, subprocess.Popen]] = []
        for i, host in enumerate(distinct):
            if _is_local(host):
                svc = TaskService(i, secret).start()
                services.append(svc)
                clients_by_idx[i] = TaskClient("127.0.0.1", svc.port,
                                               secret)
                continue
            remote = (f"{shlex.quote(sys.executable)} -m "
                      f"horovod_tpu.runner.task_server --index {i}")
            proc = subprocess.Popen(
                SSH_COMMAND_PREFIX + ["-o", "ConnectTimeout=15", host,
                                      remote],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
            # the secret travels over the ssh channel (stdin), never on a
            # command line where the remote process table would expose it
            proc.stdin.write(secret.hex() + "\n")
            proc.stdin.flush()
            procs.append(proc)
            pending.append((i, host, proc))

        def read_port(i: int, host: str, proc: subprocess.Popen) -> None:
            line = proc.stdout.readline()
            if line.startswith("HVD_TASK_PORT="):
                clients_by_idx[i] = TaskClient(
                    host, int(line.strip().split("=", 1)[1]), secret)

        readers = [threading.Thread(target=read_port, args=p, daemon=True)
                   for p in pending]
        for t in readers:
            t.start()
        for t in readers:
            t.join(timeout=30)
        missing = [h for i, h, _ in pending if i not in clients_by_idx]
        if missing:
            raise RuntimeError(
                f"task services failed to start on: {missing}")
        clients = [clients_by_idx[i] for i in range(len(distinct))]
        routable = find_routable_interfaces(clients, restrict=restrict)
        addr = pick_rendezvous_address(routable)
        if verbose:
            print(f"[hvdrun] NIC probe: rendezvous via {addr} "
                  f"(routable: {routable})", flush=True)
        return addr
    finally:
        for c in clients_by_idx.values():
            c.shutdown()
        for svc in services:
            svc.stop()
        for proc in procs:
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.terminate()


def launch_static(hosts: List[HostInfo], np: int, command: List[str],
                  env: Optional[Dict[str, str]] = None,
                  coord_addr: Optional[str] = None,
                  coord_port: Optional[int] = None,
                  nics: Optional[List[str]] = None,
                  nic_probe: bool = True,
                  verbose: bool = False,
                  output_dir: Optional[str] = None,
                  timestamp_output: bool = False) -> int:
    """Run ``command`` on every slot; return first nonzero exit code (or 0).

    Reference: ``launch_gloo`` (``gloo_run.py:226``): assignment → env →
    per-slot exec threads; any failure terminates the rest. Multi-host
    launches first resolve a mutually-routable rendezvous address through
    the task-service NIC probe (``probe_coordinator_address``).
    """
    slots = get_host_assignments(hosts, np)
    if coord_addr is None and nic_probe and \
            not all(_is_local(s.hostname) for s in slots):
        try:
            coord_addr = probe_coordinator_address(
                [s.hostname for s in slots], restrict=nics,
                verbose=verbose)
        except Exception as e:  # probing is best-effort bootstrap
            print(f"[hvdrun] NIC probe failed ({e}); falling back to "
                  f"hostname resolution", file=sys.stderr, flush=True)
    coord_addr = coord_addr or (
        "127.0.0.1" if _is_local(slots[0].hostname) else slots[0].hostname)
    coord_port = coord_port or free_port()

    results: List[Optional[int]] = [None] * np
    failure = threading.Event()

    def run_slot(idx: int, slot: SlotInfo) -> None:
        rc = 1  # anything that dies before safe_execute is a failure
        out_f = err_f = None
        try:
            cmd, run_env = slot_command(slot, command, coord_addr,
                                        coord_port, env)
            prefix = f"[{slot.rank}]<stdout/err> " if verbose else ""
            if output_dir:
                # reference --output-filename layout: <dir>/rank.N/
                # {stdout,stderr} per worker
                d = os.path.join(output_dir, f"rank.{slot.rank}")
                os.makedirs(d, exist_ok=True)
                out_f = open(os.path.join(d, "stdout"), "w", buffering=1)
                err_f = open(os.path.join(d, "stderr"), "w", buffering=1)
            rc = safe_execute(cmd, env=run_env, prefix=prefix,
                              stdout=out_f, stderr=err_f,
                              events=[failure],
                              timestamp=timestamp_output)
        except Exception as e:
            print(f"[hvdrun] rank {slot.rank} failed to launch: {e}",
                  file=sys.stderr, flush=True)
        finally:
            for f in (out_f, err_f):
                if f:
                    f.close()
            results[idx] = rc
            if rc != 0:
                failure.set()

    threads = [threading.Thread(target=run_slot, args=(i, s), daemon=True)
               for i, s in enumerate(slots)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for rc in results:
        if rc:
            return rc
    return 0
