"""HTTP key-value store + rendezvous server and client.

Reference: ``horovod/runner/http/http_server.py:35-175`` (``KVStoreHandler``
GET/PUT by scope/key; ``RendezvousHandler`` adds slot-info GET and DELETE
finalization) and ``http/http_client.py``.

Note: the default stack does NOT need this server — the TCP core performs
its own rendezvous through rank 0 and ``runner.run`` collects results via a
shared tmpdir. It is provided for custom orchestration (cross-host result
collection, external schedulers publishing worker metadata) and as the
reference-parity KV surface.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.request import Request, urlopen
from urllib.error import HTTPError

from horovod_tpu.common.retry import retry_call
from horovod_tpu.common.safe_metrics import safe_inc as _metric


class _KVHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # silence
        pass

    def _split(self) -> Tuple[str, str]:
        parts = self.path.strip("/").split("/", 1)
        scope = parts[0] if parts else ""
        key = parts[1] if len(parts) > 1 else ""
        return scope, key

    def do_GET(self):
        scope, key = self._split()
        self.server.note_request("GET", scope)
        with self.server.kv_lock:
            val = self.server.kv.get(scope, {}).get(key)
        if val is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_PUT(self):
        scope, key = self._split()
        self.server.note_request("PUT", scope)
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        # write observer BEFORE the store and the 200: the elastic
        # driver journals worker registrations through this hook, and
        # WAL ordering requires the append to be durable before the
        # writer is told its registration took (a post-ack crash must
        # not lose acknowledged control-plane state).  Outside kv_lock:
        # the hook may fsync and must not stall concurrent KV traffic.
        hook = getattr(self.server, "on_put", None)
        if hook is not None:
            try:
                hook(scope, key, body)
            except Exception:
                pass  # observation must never fail the write itself
        with self.server.kv_lock:
            self.server.kv.setdefault(scope, {})[key] = body
        self.send_response(200)
        self.end_headers()

    def do_DELETE(self):
        scope, _ = self._split()
        self.server.note_request("DELETE", scope)
        with self.server.kv_lock:
            self.server.kv.pop(scope, None)
        self.send_response(200)
        self.end_headers()


class ThreadedHTTPServer(ThreadingHTTPServer):
    """Shared server base for the repo's tiny HTTP planes (KV/rendezvous
    here, the per-worker metrics exporter in
    :mod:`horovod_tpu.metrics.exporter`, the serving replica endpoints
    in :mod:`horovod_tpu.serving.replica`): threaded, daemonized, with a
    deep accept backlog — many agents poll concurrently and the
    socketserver default backlog of 5 resets connections under bursts on
    slow machines.

    Hardened for the serving plane (docs/SERVING.md), benefiting every
    endpoint that rides it (``/metrics`` scrapes, the KV relay, the
    autopsy's ``/debug/*`` fetches):

    * **bounded concurrent-handler pool** — at most
      ``HVD_TPU_HTTP_MAX_HANDLERS`` (default 64) requests are handled
      at once; beyond that the connection gets an immediate minimal
      ``503`` + close instead of an unbounded thread pile-up (counted
      as ``hvd_http_busy_rejected_total``).  The plain ThreadingMixIn
      spawns one thread per accepted connection with no cap — a
      misbehaving poller could grow threads until the process died.
    * **per-request read/write timeouts** — every accepted socket gets
      ``HVD_TPU_HTTP_TIMEOUT_S`` (default 30) as its socket timeout, so
      one wedged or glacial client times out and frees its handler slot
      instead of pinning a thread (and, with the pool bound, eventually
      the whole plane) forever.

    Both knobs can be overridden per server via the ``max_handlers`` /
    ``handler_timeout_s`` constructor arguments (0 disables)."""

    request_queue_size = 128
    # SO_REUSEADDR, stated explicitly rather than inherited: a takeover
    # driver (docs/ELASTIC.md "Driver failover & takeover") must rebind
    # the crashed driver's advertised KV port on the same host while the
    # old socket's connections sit in TIME_WAIT — without reuse the
    # rebind fails for up to 2*MSL and every worker's poll would have to
    # ride that out too.
    allow_reuse_address = 1

    def __init__(self, server_address, RequestHandlerClass,
                 max_handlers: Optional[int] = None,
                 handler_timeout_s: Optional[float] = None) -> None:
        super().__init__(server_address, RequestHandlerClass)
        from horovod_tpu.common.config import env_float, env_int
        if max_handlers is None:
            max_handlers = env_int("HTTP_MAX_HANDLERS", 64)
        if handler_timeout_s is None:
            handler_timeout_s = env_float("HTTP_TIMEOUT_S", 30.0)
        self.handler_timeout_s = handler_timeout_s
        self._handler_slots = (
            threading.BoundedSemaphore(max_handlers)
            if max_handlers and max_handlers > 0 else None)

    def process_request(self, request, client_address):
        if self.handler_timeout_s and self.handler_timeout_s > 0:
            try:
                # read/write deadline for the whole exchange: a client
                # that stops sending (or reading) raises socket.timeout
                # in the handler, which closes the connection
                request.settimeout(self.handler_timeout_s)
            except OSError:
                pass
        if self._handler_slots is not None and \
                not self._handler_slots.acquire(blocking=False):
            self._reject_busy(request)
            return
        try:
            super().process_request(request, client_address)
        except Exception:
            if self._handler_slots is not None:
                self._handler_slots.release()
            raise

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            if self._handler_slots is not None:
                self._handler_slots.release()

    def _reject_busy(self, request) -> None:
        """Every handler slot is busy: answer a minimal 503 inline (on
        the accept thread — no new thread, no handler parse) and close.
        Explicit backpressure, never a silent drop: the client sees a
        retryable status, the operator sees the counter."""
        _metric("hvd_http_busy_rejected_total",
                "connections rejected 503 because every handler slot "
                "of a ThreadedHTTPServer was busy")
        try:
            request.sendall(
                b"HTTP/1.1 503 Service Unavailable\r\n"
                b"Retry-After: 1\r\nContent-Length: 5\r\n"
                b"Connection: close\r\n\r\nbusy\n")
        except OSError:
            pass
        try:
            self.shutdown_request(request)
        except OSError:
            pass

    def handle_error(self, request, client_address):
        # a wedged client timing out (or vanishing mid-write) is the
        # EXPECTED outcome of the per-request deadline policy, not a
        # server bug — don't spray tracebacks on stderr for it
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (TimeoutError, ConnectionError, OSError)):
            return
        super().handle_error(request, client_address)


class _KVServer(ThreadedHTTPServer):
    """Request accounting lives on the server object (one per
    KVStoreServer): the KV-relay fan-in proof (docs/ELASTIC.md "Relayed
    control-plane KV") needs each NODE's request load to be measurable —
    rank 0's root must be shown handling O(arity) worker traffic while
    the relay nodes carry the rest."""

    def note_request(self, method: str, scope: str) -> None:
        key = (method, scope)
        with self.req_lock:
            self.req_counts[key] = self.req_counts.get(key, 0) + 1
        _metric("hvd_kv_server_requests_total",
                "requests handled by this process's KV servers, "
                "per method/scope", method=method, scope=scope)


class KVStoreServer:
    """Threaded KV server (reference: ``RendezvousServer.start``,
    ``http_server.py:152``)."""

    def __init__(self, port: int = 0) -> None:
        self._httpd = self._make_server(port)
        self._httpd.kv = {}
        self._httpd.kv_lock = threading.Lock()
        self._httpd.req_counts = {}
        self._httpd.req_lock = threading.Lock()
        self._httpd.on_put = None
        self._thread: Optional[threading.Thread] = None

    @property
    def on_put(self):
        """Optional ``(scope, key, value)`` observer invoked on every
        HTTP PUT before the value is stored and acknowledged (the
        driver's journal WAL hook).  Exceptions are swallowed; the
        write always proceeds."""
        return getattr(self._httpd, "on_put", None)

    @on_put.setter
    def on_put(self, cb) -> None:
        self._httpd.on_put = cb

    def _make_server(self, port: int):
        return _KVServer(("0.0.0.0", port), _KVHandler)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self, port: Optional[int] = None) -> int:
        """Start serving.  ``port`` rebinds the server onto that specific
        port first (takeover: a fresh driver process must come up on the
        port the fleet's ``HVD_ELASTIC_KV`` already advertises).  The
        in-memory KV contents survive the rebind — the takeover path
        re-publishes into the same server object it just rebound."""
        if port is not None and port != self.port:
            self._rebind(port)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def _rebind(self, port: int) -> None:
        """Replace the bound socket with one on ``port``, keeping the KV
        dict, locks and request counts.  Retries the bind briefly: the
        dead driver's kernel socket can linger a beat past its process
        (SO_REUSEADDR clears TIME_WAIT but not a still-open listener in
        a not-yet-reaped process)."""
        import time as _time
        old = self._httpd
        try:
            old.server_close()
        except OSError:
            pass
        deadline = _time.monotonic() + 10.0
        while True:
            try:
                httpd = self._make_server(port)
                break
            except OSError:
                if _time.monotonic() >= deadline:
                    raise
                _time.sleep(0.25)
        # transplant state: the KV dict IS the control-plane content
        httpd.kv = old.kv
        httpd.kv_lock = old.kv_lock
        httpd.req_counts = old.req_counts
        httpd.req_lock = old.req_lock
        httpd.on_put = getattr(old, "on_put", None)
        self._httpd = httpd

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # a wedged handler (slow client, injected fault) is
                # outliving shutdown — the daemon thread won't block exit,
                # but leaking it silently hides the wedge from operators
                from horovod_tpu.common.logging import get_logger
                get_logger().warning(
                    "KVStoreServer.stop(): server thread still alive "
                    "after 5s join; leaking a daemon thread (port %s)",
                    self.port)

    # direct access for in-process use
    def put(self, scope: str, key: str, value: bytes) -> None:
        with self._httpd.kv_lock:
            self._httpd.kv.setdefault(scope, {})[key] = value

    def get(self, scope: str, key: str) -> Optional[bytes]:
        with self._httpd.kv_lock:
            return self._httpd.kv.get(scope, {}).get(key)

    def scope(self, scope: str) -> Dict[str, bytes]:
        with self._httpd.kv_lock:
            return dict(self._httpd.kv.get(scope, {}))

    def clear(self, scope: str) -> None:
        with self._httpd.kv_lock:
            self._httpd.kv.pop(scope, None)

    def request_counts(self) -> Dict[Tuple[str, str], int]:
        """Requests this server has handled, keyed by (method, scope) —
        the per-node load evidence behind the KV-relay fan-in proof."""
        with self._httpd.req_lock:
            return dict(self._httpd.req_counts)

    def requests_for(self, scope: str, method: Optional[str] = None) -> int:
        with self._httpd.req_lock:
            return sum(n for (m, s), n in self._httpd.req_counts.items()
                       if s == scope and (method is None or m == method))


class HTTPBusyError(OSError):
    """A 429/503 backpressure answer converted to a RETRYABLE error:
    the hardened handler pool's inline 503 busy-reject advertises
    ``Retry-After`` and means "again in a moment", not "never" — but
    ``HTTPError`` sits in the retry shield's ``give_up_on``, so
    without the conversion the first busy burst would terminally fail
    a KV call that a 50ms backoff would have saved.  Subclasses
    ``OSError`` so the relay client's broad fallback handling still
    sees it as a transient transport problem."""


def _with_retries(do, attempts: int = 4,
                  deadline_s: Optional[float] = None,
                  site: str = "http_kv",
                  count_exhausted: bool = True):
    """Transient-error shield: a busy single-core box can overflow the
    server's listen backlog under polling bursts, resetting connections
    mid-handshake; retry with jittered backoff instead of failing a
    worker.  ``deadline_s`` caps TOTAL wall time (attempts + sleeps) so
    the call's cost stays tied to the caller's intent instead of
    ``attempts × per-attempt timeout``; ``site`` labels the per-call-site
    retry metrics (``hvd_retry_*_total{site=...}``).  HTTP 429/503 —
    explicit backpressure, incl. the bounded handler pool's busy
    reject — retries like a connection reset; other HTTP statuses
    (404, 4xx) stay terminal."""
    import http.client

    def do_busy_aware():
        try:
            return do()
        except HTTPError as e:
            if e.code in (429, 503):
                raise HTTPBusyError(
                    f"HTTP {e.code} (backpressure) from {e.url}") from e
            raise

    return retry_call(
        do_busy_aware, site=site,
        retry_on=(ConnectionError, http.client.RemoteDisconnected,
                  TimeoutError, OSError),
        give_up_on=(HTTPError,),
        attempts=attempts, base_delay_s=0.05, backoff=2.0,
        max_delay_s=2.0, jitter=0.25, deadline_s=deadline_s,
        count_exhausted=count_exhausted)


def _trace_headers() -> Dict[str, str]:
    """The thread's active trace context as a ``traceparent`` header
    (docs/OBSERVABILITY.md "Causal tracing"): a KV hop made inside an
    ``activate()`` block carries its span, so relay forwards and the
    receiving server can continue the causal chain.  Empty when
    untraced — zero wire cost."""
    try:
        from horovod_tpu import tracing
        ctx = tracing.current()
        if ctx is not None:
            return {tracing.TRACEPARENT: ctx.traceparent}
    except Exception:
        pass
    return {}


def kv_put(addr: str, port: int, scope: str, key: str, value: bytes,
           timeout: float = 30.0, site: str = "http_kv.put",
           peer=None, attempts: int = 4,
           count_exhausted: bool = True) -> None:
    """``peer`` names the request's TARGET for the chaos ``kv.partition``
    seam (a worker rank for relay hops, ``"driver"`` for the root KV);
    None = target unknown, partition rules cannot match.  ``attempts=1``
    makes the call fail fast — the relay client uses it for parent hops,
    where the root fallback IS the retry."""
    req = Request(f"http://{addr}:{port}/{scope}/{key}", data=value,
                  method="PUT", headers=_trace_headers())

    def do():
        from horovod_tpu import chaos
        chaos.fire("kv.request")
        chaos.fire("kv.partition", peer=peer)
        return urlopen(req, timeout=timeout).read()

    _with_retries(do, attempts=attempts, deadline_s=2.0 * timeout,
                  site=site, count_exhausted=count_exhausted)


def kv_get(addr: str, port: int, scope: str, key: str,
           timeout: float = 30.0, site: str = "http_kv.get",
           peer=None, attempts: int = 4,
           count_exhausted: bool = True) -> Optional[bytes]:
    def do():
        from horovod_tpu import chaos
        chaos.fire("kv.request")
        chaos.fire("kv.partition", peer=peer)
        req = Request(f"http://{addr}:{port}/{scope}/{key}",
                      headers=_trace_headers())
        return urlopen(req, timeout=timeout).read()

    try:
        return _with_retries(do, attempts=attempts,
                             deadline_s=2.0 * timeout, site=site,
                             count_exhausted=count_exhausted)
    except HTTPError as e:
        if e.code == 404:
            return None
        raise


