"""Process execution with group cleanup and output pumping.

Reference: ``horovod/runner/common/util/safe_shell_exec.py`` — spawn in a new
process group, pump stdout/stderr with threads, kill the whole group on
termination so stray grandchildren don't leak.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

GRACEFUL_TERMINATION_TIME_S = 5


def _pump(stream, out, prefix: str = "", timestamp: bool = False) -> None:
    for line in iter(stream.readline, b""):
        try:
            text = line.decode(errors="replace")
            stamp = ""
            if timestamp:
                # reference: --prefix-output-with-timestamp
                # (safe_shell_exec prepend_context)
                stamp = time.strftime("%Y-%m-%d %H:%M:%S") + " "
            out.write(stamp + prefix + text)
            out.flush()
        except ValueError:
            break
    stream.close()


def safe_execute(command: List[str], env: Optional[Dict[str, str]] = None,
                 stdout=None, stderr=None, prefix: str = "",
                 events: Optional[List[threading.Event]] = None,
                 timestamp: bool = False,
                 on_start=None) -> int:
    """Run command; if any event fires, terminate the process group
    (reference: ``safe_shell_exec.execute``).  ``on_start(pid)`` is
    called right after the spawn — the elastic driver journals worker
    PIDs through it, so a takeover driver can adopt (monitor, and if
    need be kill) workers that outlived the process that spawned them.
    Note the spawn uses ``preexec_fn=os.setsid``: each worker leads its
    OWN process group, which is exactly why it survives its driver."""
    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr
    proc = subprocess.Popen(
        command, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        preexec_fn=os.setsid)
    if on_start is not None:
        try:
            on_start(proc.pid)
        except Exception:
            from horovod_tpu.common.logging import get_logger
            get_logger().warning("safe_execute: on_start callback failed",
                                 exc_info=True)
    pumps = [
        threading.Thread(target=_pump,
                         args=(proc.stdout, stdout, prefix, timestamp),
                         daemon=True),
        threading.Thread(target=_pump,
                         args=(proc.stderr, stderr, prefix, timestamp),
                         daemon=True),
    ]
    for t in pumps:
        t.start()

    stop = threading.Event()

    def watch_events() -> None:
        while not stop.is_set():
            for ev in events or []:
                if ev.is_set():
                    terminate_process_group(proc)
                    return
            time.sleep(0.1)

    watcher = None
    if events:
        watcher = threading.Thread(target=watch_events, daemon=True)
        watcher.start()

    rc = proc.wait()
    stop.set()
    for t in pumps:
        t.join(timeout=2)
    if watcher:
        watcher.join(timeout=1)
    return rc


def terminate_process_group(proc: subprocess.Popen) -> None:
    """SIGTERM the group, escalate to SIGKILL (reference:
    ``safe_shell_exec`` graceful termination)."""
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except ProcessLookupError:
        return
    deadline = time.time() + GRACEFUL_TERMINATION_TIME_S
    while time.time() < deadline:
        if proc.poll() is not None:
            return
        time.sleep(0.1)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except ProcessLookupError:
        pass
