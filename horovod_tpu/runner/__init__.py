"""Launcher package (reference: ``horovod/runner/``).

Also hosts the interactive API: ``horovod_tpu.runner.run(fn, np=2)`` runs
``fn`` in np local worker processes and returns the per-rank results
(reference: ``horovod.run``, ``runner/__init__.py:92``).
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
from typing import Any, Callable, List, Optional

from horovod_tpu.runner.hosts import HostInfo
from horovod_tpu.runner.exec_run import launch_static

_WORKER_SNIPPET = """
import os, pickle, sys
with open(os.environ["HVD_RUN_FN"], "rb") as f:
    fn, args, kwargs = pickle.load(f)
import horovod_tpu as hvd
hvd.init()
result = fn(*args, **kwargs)
out = os.path.join(os.environ["HVD_RUN_OUT"],
                   f"result_{hvd.rank()}.pkl")
with open(out + ".tmp", "wb") as f:
    pickle.dump(result, f)
os.replace(out + ".tmp", out)
hvd.shutdown()
"""


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        np: int = 1, env: Optional[dict] = None,
        use_cloudpickle: bool = True) -> List[Any]:
    """Run ``fn`` under np local workers; returns per-rank results in rank
    order (reference: ``horovod.run`` interactive mode via KV store,
    ``runner/launch.py:594-614`` — here via a tmpdir instead of HTTP)."""
    kwargs = kwargs or {}
    # cloudpickle serializes closures/lambdas by value (the reference uses
    # it for the same purpose in run-func mode)
    pickler = pickle
    if use_cloudpickle:
        try:
            import cloudpickle as pickler
        except ImportError:
            pass
    with tempfile.TemporaryDirectory(prefix="hvd_run_") as tmp:
        fn_path = os.path.join(tmp, "fn.pkl")
        with open(fn_path, "wb") as f:
            pickler.dump((fn, args, kwargs), f)
        wenv = dict(env if env is not None else os.environ)
        wenv["HVD_RUN_FN"] = fn_path
        wenv["HVD_RUN_OUT"] = tmp
        rc = launch_static([HostInfo("localhost", np)], np,
                           [sys.executable, "-c", _WORKER_SNIPPET],
                           env=wenv)
        if rc != 0:
            raise RuntimeError(f"hvd.run workers failed with exit code {rc}")
        results = []
        for r in range(np):
            with open(os.path.join(tmp, f"result_{r}.pkl"), "rb") as f:
                results.append(pickle.load(f))
        return results
