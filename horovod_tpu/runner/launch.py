"""``hvdrun`` — the launcher CLI.

Reference: ``horovod/runner/launch.py`` (``run_commandline`` at :763,
``_run`` at :736 dispatching static vs elastic, ``parse_args`` with the full
env-knob mirror via ``config_parser``). TPU-native differences: one worker
process per HOST (driving all local chips) instead of per accelerator; the
controller is the native TCP core (no mpirun/jsrun variants).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List

from horovod_tpu.runner.hosts import (HostInfo, parse_hostfile, parse_hosts)
from horovod_tpu.runner.exec_run import launch_static
from horovod_tpu.version import __version__


def parse_args(argv: List[str]) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu distributed job "
                    "(Horovod-class launcher for TPU hosts)")
    p.add_argument("--version", action="version", version=__version__)
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="print available frameworks/controllers/ops and "
                        "exit (reference: horovodrun --check-build)")
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="number of worker processes (TPU hosts)")
    p.add_argument("-H", "--hosts", default=None,
                   help='host list "h1:slots,h2:slots"')
    p.add_argument("--hostfile", default=None,
                   help="hostfile with lines 'host slots=N'")
    p.add_argument("--tpu", action="store_true",
                   help="enumerate the TPU pod slice's worker VMs from "
                        "the GCE metadata service instead of -H/--hostfile "
                        "(the TPU analog of the reference's MPI/LSF "
                        "environment detection); with --min-np, elastic "
                        "discovery re-reads the slice each refresh")
    p.add_argument("--verbose", action="store_true")
    # elastic (reference: --min-np/--max-np/--host-discovery-script)
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--host-discovery-script", default=None,
                   help="script printing 'host:slots' lines; enables "
                        "elastic mode")
    p.add_argument("--reset-limit", type=int, default=None,
                   help="max elastic relaunch generations before giving up")
    # knobs mirrored to env (reference: config_parser.py — full set; see
    # docs/KNOBS.md for the table)
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--hierarchical-allreduce", action="store_true")
    p.add_argument("--hierarchical-allgather", action="store_true")
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--autotune-log-file", default=None)
    p.add_argument("--autotune-warmup-samples", type=int, default=None)
    p.add_argument("--autotune-steps-per-sample", type=int, default=None)
    p.add_argument("--autotune-bayes-opt-max-samples", type=int,
                   default=None)
    p.add_argument("--autotune-gaussian-process-noise", type=float,
                   default=None)
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--no-stall-check", action="store_true")
    p.add_argument("--stall-warning-timeout-seconds", type=float,
                   default=None)
    p.add_argument("--stall-shutdown-timeout-seconds", type=float,
                   default=None)
    # back-compat alias for the r1 flag name
    p.add_argument("--stall-timeout-seconds", type=float, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--gloo-timeout-seconds", type=float, default=None,
                   help="rendezvous/mesh connect deadline")
    p.add_argument("--network-interfaces", "--nics", dest="nics",
                   default=None,
                   help="comma-separated NIC allowlist for the multi-host "
                        "routability probe (reference: --network-interfaces)")
    p.add_argument("--no-nic-probe", action="store_true",
                   help="skip the task-service NIC probe on multi-host "
                        "launches")
    p.add_argument("--thread-affinity", type=int, default=None,
                   help="pin the core background thread to this CPU")
    p.add_argument("--output-filename", default=None,
                   help="directory collecting per-worker output as "
                        "<dir>/rank.N/{stdout,stderr} (reference: "
                        "horovodrun --output-filename)")
    p.add_argument("--prefix-output-with-timestamp", action="store_true",
                   help="timestamp every pumped worker output line "
                        "(reference flag of the same name)")
    p.add_argument("--log-level", default=None,
                   choices=["TRACE", "DEBUG", "INFO", "WARNING", "ERROR",
                            "FATAL"])
    p.add_argument("--log-hide-timestamp", action="store_true")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program and args to run on every worker")
    args = p.parse_args(argv)
    if not args.command and not args.check_build:
        p.error("no command given")
    # one host source only — enforced here so the elastic path can't
    # silently ignore a conflicting -H/--hostfile/--host-discovery-script
    if sum(bool(x) for x in (args.hosts, args.hostfile, args.tpu,
                             args.host_discovery_script)) > 1:
        p.error("specify only one of -H/--hosts, --hostfile, --tpu, "
                "--host-discovery-script")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    return args


def knobs_to_env(args: argparse.Namespace) -> Dict[str, str]:
    """CLI knob → env mirror (reference: ``config_parser.set_env_from_args``)."""
    env: Dict[str, str] = {}

    def put(flag_value, name, convert=str):
        if flag_value is not None and flag_value is not False:
            env[name] = "1" if flag_value is True else convert(flag_value)

    put(None if args.fusion_threshold_mb is None
        else int(args.fusion_threshold_mb * 1024 * 1024),
        "HOROVOD_FUSION_THRESHOLD")
    put(args.cycle_time_ms, "HOROVOD_CYCLE_TIME")
    put(args.cache_capacity, "HOROVOD_CACHE_CAPACITY")
    put(args.hierarchical_allreduce, "HOROVOD_HIERARCHICAL_ALLREDUCE")
    put(args.hierarchical_allgather, "HOROVOD_HIERARCHICAL_ALLGATHER")
    put(args.autotune, "HOROVOD_AUTOTUNE")
    put(args.autotune_log_file, "HOROVOD_AUTOTUNE_LOG")
    put(args.autotune_warmup_samples, "HOROVOD_AUTOTUNE_WARMUP_SAMPLES")
    put(args.autotune_steps_per_sample,
        "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE")
    put(args.autotune_bayes_opt_max_samples,
        "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES")
    put(args.autotune_gaussian_process_noise,
        "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE")
    put(args.timeline_filename or None, "HOROVOD_TIMELINE")
    put(args.timeline_mark_cycles, "HOROVOD_TIMELINE_MARK_CYCLES")
    put(args.no_stall_check, "HOROVOD_STALL_CHECK_DISABLE")
    put(args.stall_warning_timeout_seconds
        if args.stall_warning_timeout_seconds is not None
        else args.stall_timeout_seconds,
        "HOROVOD_STALL_CHECK_TIME_SECONDS")
    put(args.stall_shutdown_timeout_seconds,
        "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS")
    put(args.gloo_timeout_seconds, "HOROVOD_GLOO_TIMEOUT_SECONDS")
    put(args.thread_affinity, "HOROVOD_THREAD_AFFINITY")
    put(args.log_level, "HOROVOD_LOG_LEVEL")
    put(args.log_hide_timestamp, "HOROVOD_LOG_HIDE_TIME")
    return env


def resolve_hosts(args: argparse.Namespace) -> List[HostInfo]:
    if sum(bool(x) for x in
           (args.hosts, args.hostfile, getattr(args, "tpu", False))) > 1:
        raise ValueError(
            "Specify only one of --hosts, --hostfile, --tpu")
    if getattr(args, "tpu", False):
        from horovod_tpu.runner.tpu_discovery import tpu_pod_hosts
        return tpu_pod_hosts()
    if args.hostfile:
        return parse_hostfile(args.hostfile)
    if args.hosts:
        return parse_hosts(args.hosts)
    np = args.num_proc or 1
    return [HostInfo("localhost", np)]


def check_build() -> str:
    """Capability report (reference: ``check_build``, ``launch.py:110-145``):
    which frameworks this install can drive and which data/control planes
    are built, in the reference's checkbox format."""
    import importlib.util

    from horovod_tpu.common import basics

    def mark(v):
        return "X" if v else " "

    def has(mod):
        try:
            return importlib.util.find_spec(mod) is not None
        except (ImportError, ValueError):
            return False

    return f"""\
horovod_tpu v{__version__}:

Available Frameworks:
    [{mark(has('jax'))}] JAX (native surface)
    [{mark(has('tensorflow'))}] TensorFlow
    [{mark(has('torch'))}] PyTorch
    [{mark(has('keras') or has('tensorflow'))}] Keras

Available Controllers:
    [{mark(basics.tcp_core_built())}] TCP core (libhvdcore)

Available Tensor Operations:
    [{mark(basics.xla_built())}] XLA (in-graph + eager data plane)
    [{mark(basics.tcp_core_built())}] TCP core (host collectives)
    [X] Local (single process)"""


def run_commandline(argv: List[str] = None) -> int:
    """Reference: ``run_commandline`` (``launch.py:763``)."""
    args = parse_args(argv if argv is not None else sys.argv[1:])
    if args.check_build:
        print(check_build())
        return 0
    env = dict(os.environ)
    env.update(knobs_to_env(args))

    elastic = args.host_discovery_script is not None or \
        args.min_np is not None
    if elastic:
        from horovod_tpu.runner.elastic.driver import run_elastic
        from horovod_tpu.runner.elastic.discovery import (
            FixedHosts, HostDiscoveryScript)
        if args.host_discovery_script:
            discovery = HostDiscoveryScript(args.host_discovery_script)
        elif args.tpu:
            from horovod_tpu.runner.tpu_discovery import TpuPodDiscovery
            discovery = TpuPodDiscovery()
        else:
            discovery = FixedHosts(resolve_hosts(args))
        return run_elastic(
            discovery, args.num_proc, args.command,
            min_np=args.min_np or 1, max_np=args.max_np,
            env=env, verbose=args.verbose, reset_limit=args.reset_limit,
            timestamp_output=args.prefix_output_with_timestamp)

    hosts = resolve_hosts(args)
    np = args.num_proc or sum(h.slots for h in hosts)
    nics = [n.strip() for n in args.nics.split(",") if n.strip()] \
        if args.nics else None
    return launch_static(hosts, np, args.command, env=env,
                         nics=nics, nic_probe=not args.no_nic_probe,
                         verbose=args.verbose,
                         output_dir=args.output_filename,
                         timestamp_output=args.prefix_output_with_timestamp)


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
