"""``hvdrun`` — the launcher CLI.

Reference: ``horovod/runner/launch.py`` (``run_commandline`` at :763,
``_run`` at :736 dispatching static vs elastic, ``parse_args`` with the full
env-knob mirror via ``config_parser``). TPU-native differences: one worker
process per HOST (driving all local chips) instead of per accelerator; the
controller is the native TCP core (no mpirun/jsrun variants).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List

from horovod_tpu.runner.hosts import (HostInfo, parse_hostfile, parse_hosts)
from horovod_tpu.runner.exec_run import launch_static
from horovod_tpu.version import __version__


def parse_args(argv: List[str]) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu distributed job "
                    "(Horovod-class launcher for TPU hosts)")
    p.add_argument("--version", action="version", version=__version__)
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="print available frameworks/controllers/ops and "
                        "exit (reference: horovodrun --check-build)")
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="number of worker processes (TPU hosts)")
    p.add_argument("-H", "--hosts", default=None,
                   help='host list "h1:slots,h2:slots"')
    p.add_argument("--hostfile", default=None,
                   help="hostfile with lines 'host slots=N'")
    p.add_argument("--tpu", action="store_true",
                   help="enumerate the TPU pod slice's worker VMs from "
                        "the GCE metadata service instead of -H/--hostfile "
                        "(the TPU analog of the reference's MPI/LSF "
                        "environment detection); with --min-np, elastic "
                        "discovery re-reads the slice each refresh")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--config-file", default=None,
                   help="YAML file with the reference's config schema "
                        "(params/autotune/timeline/stall_check/"
                        "library_options/logging sections); explicit CLI "
                        "flags override it (reference: config_parser.py)")
    p.add_argument("--start-timeout", type=float, default=None,
                   help="seconds for all workers to start and connect "
                        "(static: mesh-connect deadline; elastic: initial "
                        "min-host wait; reference flag of the same name)")
    p.add_argument("--elastic-timeout", type=float, default=None,
                   help="seconds to re-reach min-np slots after a world "
                        "change (reference flag of the same name)")
    p.add_argument("-s", "--slots", "--slots-per-host",
                   dest="slots_per_host", type=int, default=None,
                   help="default slots for discovered hosts that do not "
                        "state their own ':slots' (reference: --slots)")
    # elastic (reference: --min-np/--max-np/--host-discovery-script)
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--host-discovery-script", default=None,
                   help="script printing 'host:slots' lines; enables "
                        "elastic mode")
    p.add_argument("--reset-limit", type=int, default=None,
                   help="max elastic relaunch generations before giving up")
    # control-plane HA (docs/ELASTIC.md "Driver failover & takeover")
    p.add_argument("--driver-journal-dir", default=None,
                   help="journal every elastic-driver decision to this "
                        "directory and supervise the driver: a crashed "
                        "driver is respawned with --takeover and adopts "
                        "the running workers (mirrors "
                        "HVD_TPU_DRIVER_JOURNAL_DIR)")
    p.add_argument("--takeover", action="store_true",
                   help="replay the driver journal and adopt a running "
                        "elastic job instead of launching a new one "
                        "(requires a journal dir via "
                        "--driver-journal-dir or "
                        "HVD_TPU_DRIVER_JOURNAL_DIR)")
    p.add_argument("--no-driver-supervisor", action="store_true",
                   help="run the elastic driver in THIS process even "
                        "when a journal dir is configured (no crash "
                        "respawn; the supervisor uses it for its own "
                        "child)")
    # knobs mirrored to env (reference: config_parser.py — full set; see
    # docs/KNOBS.md for the table)
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--hierarchical-allreduce", action="store_true")
    p.add_argument("--hierarchical-allgather", action="store_true")
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--autotune-log-file", default=None)
    p.add_argument("--autotune-warmup-samples", type=int, default=None)
    p.add_argument("--autotune-steps-per-sample", type=int, default=None)
    p.add_argument("--autotune-bayes-opt-max-samples", type=int,
                   default=None)
    p.add_argument("--autotune-gaussian-process-noise", type=float,
                   default=None)
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--no-stall-check", action="store_true")
    p.add_argument("--stall-warning-timeout-seconds", type=float,
                   default=None)
    p.add_argument("--stall-shutdown-timeout-seconds", type=float,
                   default=None)
    # back-compat alias for the r1 flag name
    p.add_argument("--stall-timeout-seconds", type=float, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--gloo-timeout-seconds", type=float, default=None,
                   help="rendezvous/mesh connect deadline")
    p.add_argument("--network-interfaces", "--nics", dest="nics",
                   default=None,
                   help="comma-separated NIC allowlist for the multi-host "
                        "routability probe (reference: --network-interfaces)")
    p.add_argument("--no-nic-probe", action="store_true",
                   help="skip the task-service NIC probe on multi-host "
                        "launches")
    p.add_argument("--thread-affinity", type=int, default=None,
                   help="pin the core background thread to this CPU")
    p.add_argument("--output-filename", default=None,
                   help="directory collecting per-worker output as "
                        "<dir>/rank.N/{stdout,stderr} (reference: "
                        "horovodrun --output-filename)")
    p.add_argument("--prefix-output-with-timestamp", action="store_true",
                   help="timestamp every pumped worker output line "
                        "(reference flag of the same name)")
    p.add_argument("--log-level", default=None,
                   choices=["TRACE", "DEBUG", "INFO", "WARNING", "ERROR",
                            "FATAL"])
    p.add_argument("--log-hide-timestamp", action="store_true")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program and args to run on every worker")
    args = p.parse_args(argv)
    if not args.command and not args.check_build:
        p.error("no command given")
    # one host source only — enforced here so the elastic path can't
    # silently ignore a conflicting -H/--hostfile/--host-discovery-script
    if sum(bool(x) for x in (args.hosts, args.hostfile, args.tpu,
                             args.host_discovery_script)) > 1:
        p.error("specify only one of -H/--hosts, --hostfile, --tpu, "
                "--host-discovery-script")
    if args.takeover and args.host_discovery_script is None \
            and args.min_np is None:
        p.error("--takeover requires elastic mode (--min-np or "
                "--host-discovery-script)")
    # launcher flags end where the user command begins: the probe below
    # must never see the command's own options
    launcher_argv = list(argv)[:len(argv) - len(args.command)]
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if args.config_file:
        apply_config_file(args, _explicit_flags(p, launcher_argv), p)
    validate_config_args(args)
    return args


def _explicit_flags(parser: argparse.ArgumentParser,
                    launcher_argv: List[str]) -> set:
    """Dests the user actually passed on the CLI (these override the
    config file, reference: ``config_parser``'s override_args). A probe
    parser with ``SUPPRESS`` defaults leaves only explicitly-given
    attributes on its namespace; abbreviation rules match the main
    parser so an abbreviated flag still counts as explicit."""
    probe = argparse.ArgumentParser(add_help=False)
    for a in parser._actions:
        if not a.option_strings or isinstance(
                a, (argparse._HelpAction, argparse._VersionAction)):
            continue
        kwargs = {"dest": a.dest, "default": argparse.SUPPRESS}
        if a.nargs == 0:  # store_true-style flags take no value
            kwargs["action"] = "store_true"
        probe.add_argument(*a.option_strings, **kwargs)
    ns, _ = probe.parse_known_args(launcher_argv)
    return set(vars(ns).keys())


# YAML section -> {config key -> args attribute}; keys accept both
# hyphen and underscore spelling. Schema mirrors the reference's
# (``config_parser.set_args_from_config``) with this launcher's arg names.
_CONFIG_SECTIONS = {
    "params": {
        "fusion_threshold_mb": "fusion_threshold_mb",
        "cycle_time_ms": "cycle_time_ms",
        "cache_capacity": "cache_capacity",
        "hierarchical_allreduce": "hierarchical_allreduce",
        "hierarchical_allgather": "hierarchical_allgather",
    },
    "autotune": {
        "enabled": "autotune",
        "log_file": "autotune_log_file",
        "warmup_samples": "autotune_warmup_samples",
        "steps_per_sample": "autotune_steps_per_sample",
        "bayes_opt_max_samples": "autotune_bayes_opt_max_samples",
        "gaussian_process_noise": "autotune_gaussian_process_noise",
    },
    "timeline": {
        "filename": "timeline_filename",
        "mark_cycles": "timeline_mark_cycles",
    },
    "stall_check": {
        # "enabled" inverts onto no_stall_check below
        "warning_time_seconds": "stall_warning_timeout_seconds",
        "shutdown_time_seconds": "stall_shutdown_timeout_seconds",
    },
    "library_options": {
        "thread_affinity": "thread_affinity",
        "gloo_timeout_seconds": "gloo_timeout_seconds",
    },
    "logging": {
        "level": "log_level",
        "hide_timestamp": "log_hide_timestamp",
    },
    "": {  # top-level keys
        "verbose": "verbose",
        "start_timeout": "start_timeout",
        "elastic_timeout": "elastic_timeout",
        "slots": "slots_per_host",
    },
}


def _coerce_bool(v):
    """YAML booleans plus their common string spellings — ``bool('false')``
    would silently be True."""
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)) and v in (0, 1):
        return bool(v)
    if isinstance(v, str):
        s = v.strip().lower()
        if s in ("1", "true", "yes", "on"):
            return True
        if s in ("0", "false", "no", "off"):
            return False
    raise ValueError(f"not a boolean: {v!r}")


def apply_config_file(args: argparse.Namespace, explicit: set,
                      parser: argparse.ArgumentParser) -> None:
    """Fill non-explicit args from the YAML config (reference:
    ``config_parser.set_args_from_config``). Values are coerced through
    the flag's own argparse type so ``start-timeout: '120'`` (a quoted
    number) behaves like the CLI flag would."""
    import yaml

    with open(args.config_file) as f:
        config = yaml.safe_load(f) or {}

    types = {a.dest: (_coerce_bool if a.nargs == 0 else a.type)
             for a in parser._actions if a.option_strings}

    def norm(d):
        return {str(k).replace("-", "_"): v for k, v in d.items()} \
            if isinstance(d, dict) else {}

    config = norm(config)
    for section, mapping in _CONFIG_SECTIONS.items():
        values = config if section == "" else norm(config.get(section))
        for key, dest in mapping.items():
            if dest in explicit:
                continue
            v = values.get(key)
            if v is not None:
                coerce = types.get(dest)
                if coerce is not None:
                    try:
                        v = coerce(v)
                    except (TypeError, ValueError) as e:
                        raise ValueError(
                            f"config file {args.config_file}: key "
                            f"{key!r} = {v!r} is not a valid "
                            f"{getattr(coerce, '__name__', coerce)}") \
                            from e
                setattr(args, dest, v)
    stall = norm(config.get("stall_check"))
    if "enabled" in stall and "no_stall_check" not in explicit:
        args.no_stall_check = not stall["enabled"]


def validate_config_args(args: argparse.Namespace) -> None:
    """Reject negatives the env parser would otherwise carry through
    (reference: ``config_parser.validate_config_args``)."""
    for name in ("fusion_threshold_mb", "cycle_time_ms", "cache_capacity",
                 "autotune_warmup_samples", "autotune_steps_per_sample",
                 "autotune_bayes_opt_max_samples",
                 "stall_warning_timeout_seconds",
                 "stall_shutdown_timeout_seconds", "thread_affinity",
                 "gloo_timeout_seconds", "start_timeout",
                 "elastic_timeout"):
        v = getattr(args, name, None)
        if v is not None and v < 0:
            raise ValueError(f"{name}={v} must be >= 0")
    slots = getattr(args, "slots_per_host", None)
    if slots is not None and slots < 1:
        raise ValueError(f"slots_per_host={slots} must be >= 1")
    noise = getattr(args, "autotune_gaussian_process_noise", None)
    if noise is not None and not (0 <= noise <= 1):
        raise ValueError(
            f"autotune_gaussian_process_noise={noise} must be in [0, 1]")


def knobs_to_env(args: argparse.Namespace) -> Dict[str, str]:
    """CLI knob → env mirror (reference: ``config_parser.set_env_from_args``)."""
    env: Dict[str, str] = {}

    def put(flag_value, name, convert=str):
        if flag_value is not None and flag_value is not False:
            env[name] = "1" if flag_value is True else convert(flag_value)

    put(None if args.fusion_threshold_mb is None
        else int(args.fusion_threshold_mb * 1024 * 1024),
        "HOROVOD_FUSION_THRESHOLD")
    put(args.cycle_time_ms, "HOROVOD_CYCLE_TIME")
    put(args.cache_capacity, "HOROVOD_CACHE_CAPACITY")
    put(args.hierarchical_allreduce, "HOROVOD_HIERARCHICAL_ALLREDUCE")
    put(args.hierarchical_allgather, "HOROVOD_HIERARCHICAL_ALLGATHER")
    put(args.autotune, "HOROVOD_AUTOTUNE")
    put(args.autotune_log_file, "HOROVOD_AUTOTUNE_LOG")
    put(args.autotune_warmup_samples, "HOROVOD_AUTOTUNE_WARMUP_SAMPLES")
    put(args.autotune_steps_per_sample,
        "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE")
    put(args.autotune_bayes_opt_max_samples,
        "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES")
    put(args.autotune_gaussian_process_noise,
        "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE")
    put(args.timeline_filename or None, "HOROVOD_TIMELINE")
    put(args.timeline_mark_cycles, "HOROVOD_TIMELINE_MARK_CYCLES")
    put(args.no_stall_check, "HOROVOD_STALL_CHECK_DISABLE")
    put(args.stall_warning_timeout_seconds
        if args.stall_warning_timeout_seconds is not None
        else args.stall_timeout_seconds,
        "HOROVOD_STALL_CHECK_TIME_SECONDS")
    put(args.stall_shutdown_timeout_seconds,
        "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS")
    put(args.gloo_timeout_seconds, "HOROVOD_GLOO_TIMEOUT_SECONDS")
    put(args.thread_affinity, "HOROVOD_THREAD_AFFINITY")
    put(args.log_level, "HOROVOD_LOG_LEVEL")
    put(args.log_hide_timestamp, "HOROVOD_LOG_HIDE_TIME")
    return env


def resolve_hosts(args: argparse.Namespace) -> List[HostInfo]:
    if sum(bool(x) for x in
           (args.hosts, args.hostfile, getattr(args, "tpu", False))) > 1:
        raise ValueError(
            "Specify only one of --hosts, --hostfile, --tpu")
    if getattr(args, "tpu", False):
        from horovod_tpu.runner.tpu_discovery import tpu_pod_hosts
        return tpu_pod_hosts()
    if args.hostfile:
        return parse_hostfile(args.hostfile)
    if args.hosts:
        return parse_hosts(args.hosts)
    np = args.num_proc or 1
    return [HostInfo("localhost", np)]


def check_build() -> str:
    """Capability report (reference: ``check_build``, ``launch.py:110-145``):
    which frameworks this install can drive and which data/control planes
    are built, in the reference's checkbox format."""
    import importlib.util

    from horovod_tpu.common import basics

    def mark(v):
        return "X" if v else " "

    def has(mod):
        try:
            return importlib.util.find_spec(mod) is not None
        except (ImportError, ValueError):
            return False

    return f"""\
horovod_tpu v{__version__}:

Available Frameworks:
    [{mark(has('jax'))}] JAX (native surface)
    [{mark(has('tensorflow'))}] TensorFlow
    [{mark(has('torch'))}] PyTorch
    [{mark(has('keras') or has('tensorflow'))}] Keras

Available Controllers:
    [{mark(basics.tcp_core_built())}] TCP core (libhvdcore)

Available Tensor Operations:
    [{mark(basics.xla_built())}] XLA (in-graph + eager data plane)
    [{mark(basics.tcp_core_built())}] TCP core (host collectives)
    [X] Local (single process)"""


def supervise_driver(argv: List[str], env: Dict[str, str],
                     journal_dir: str, takeover: bool = False) -> int:
    """Driver supervisor loop (docs/ELASTIC.md "Driver failover &
    takeover"): run the elastic driver as a CHILD process and, when it
    dies without journaling a ``clean_exit``, respawn it with
    ``--takeover`` so it replays the journal and adopts the running
    fleet.  Workers lead their own process groups (safe_exec setsid),
    so a driver crash — or a SIGKILL from the chaos ``driver`` seam —
    leaves them training; the respawned driver re-publishes the last
    committed world verbatim and they ride the outage out inside
    ``HVD_TPU_DRIVER_OUTAGE_GRACE_S`` without re-meshing."""
    import subprocess
    from horovod_tpu.common.config import env_int
    from horovod_tpu.common.logging import get_logger
    from horovod_tpu.runner.elastic import journal as journal_mod
    log = get_logger()
    # --takeover is the supervisor's decision from here on: the child is
    # respawned into takeover only after a crash is confirmed
    base = [a for a in argv if a != "--takeover"]
    max_takeovers = max(0, env_int("DRIVER_MAX_TAKEOVERS", 3))
    path = os.path.join(journal_dir, journal_mod.JOURNAL_NAME)
    takeovers = 0
    while True:
        cmd = [sys.executable, "-m", "horovod_tpu.runner.launch"] + \
            (["--takeover"] if takeover else []) + base
        child_env = dict(env)
        child_env["HVD_TPU_DRIVER_SUPERVISED"] = "1"
        child_env["HVD_TPU_DRIVER_JOURNAL_DIR"] = journal_dir
        rc = subprocess.run(cmd, env=child_env).returncode
        try:
            state = journal_mod.load(path)
        except Exception as exc:
            log.error("driver supervisor: journal %s unreadable (%s); "
                      "passing driver rc %d through", path, exc, rc)
            return rc
        if state.clean_exit is not None:
            # the driver finished ON PURPOSE (success or classified
            # failure) — its verdict stands, no takeover
            return rc
        takeovers += 1
        if takeovers > max_takeovers:
            log.error(
                "driver supervisor: driver died again (rc %d) after %d "
                "takeover(s); HVD_TPU_DRIVER_MAX_TAKEOVERS exhausted — "
                "giving up (docs/TROUBLESHOOTING.md \"My driver died\")",
                rc, takeovers - 1)
            return rc or 1
        try:
            state.check_takeover()
        except journal_mod.TakeoverRefused as exc:
            log.error(
                "driver supervisor: driver died (rc %d) but takeover is "
                "refused: %s — recover manually (docs/TROUBLESHOOTING.md "
                "\"My driver died\")", rc, exc)
            return rc or 1
        log.warning(
            "driver supervisor: driver died (rc %d) without a clean "
            "exit; respawning into journal takeover %d/%d",
            rc, takeovers, max_takeovers)
        takeover = True


def run_commandline(argv: List[str] = None) -> int:
    """Reference: ``run_commandline`` (``launch.py:763``)."""
    args = parse_args(argv if argv is not None else sys.argv[1:])
    if args.check_build:
        print(check_build())
        return 0
    env = dict(os.environ)
    env.update(knobs_to_env(args))

    elastic = args.host_discovery_script is not None or \
        args.min_np is not None
    if elastic:
        from horovod_tpu.runner.elastic.driver import run_elastic
        from horovod_tpu.runner.elastic.discovery import (
            FixedHosts, HostDiscoveryScript)
        if args.host_discovery_script:
            discovery = HostDiscoveryScript(
                args.host_discovery_script,
                default_slots=1 if args.slots_per_host is None
                else args.slots_per_host)
        elif args.tpu:
            from horovod_tpu.runner.tpu_discovery import TpuPodDiscovery
            discovery = TpuPodDiscovery()
        else:
            discovery = FixedHosts(resolve_hosts(args))
        journal_dir = args.driver_journal_dir or \
            env.get("HVD_TPU_DRIVER_JOURNAL_DIR") or None
        if journal_dir:
            # the driver (and the supervisor's respawned child) read the
            # dir from the environment; a CLI flag must reach them too
            env["HVD_TPU_DRIVER_JOURNAL_DIR"] = journal_dir
        if journal_dir and not args.no_driver_supervisor \
                and os.environ.get("HVD_TPU_DRIVER_SUPERVISED") != "1":
            return supervise_driver(
                list(argv) if argv is not None else sys.argv[1:],
                env, journal_dir, takeover=args.takeover)
        return run_elastic(
            discovery, args.num_proc, args.command,
            min_np=args.min_np or 1, max_np=args.max_np,
            env=env, verbose=args.verbose, reset_limit=args.reset_limit,
            timestamp_output=args.prefix_output_with_timestamp,
            start_timeout=args.start_timeout,
            elastic_timeout=args.elastic_timeout,
            journal_dir=journal_dir, takeover=args.takeover)

    if args.start_timeout is not None:
        # STATIC path only (elastic generations use --elastic-timeout for
        # re-scale waits — a short start deadline must not bound their
        # mesh reconnects): every worker must reach the coordinator mesh
        # inside this window. An explicit --gloo-timeout-seconds wins —
        # knobs_to_env already set it above.
        env.setdefault("HOROVOD_GLOO_TIMEOUT_SECONDS",
                       str(args.start_timeout))
    hosts = resolve_hosts(args)
    np = args.num_proc or sum(h.slots for h in hosts)
    nics = [n.strip() for n in args.nics.split(",") if n.strip()] \
        if args.nics else None
    return launch_static(hosts, np, args.command, env=env,
                         nics=nics, nic_probe=not args.no_nic_probe,
                         verbose=args.verbose,
                         output_dir=args.output_filename,
                         timestamp_output=args.prefix_output_with_timestamp)


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
