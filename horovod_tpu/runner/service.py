"""Driver/task bootstrap services with NIC probing.

Reference: ``horovod/runner/driver/driver_service.py:49-235`` +
``horovod/common/service/task_service.py:108`` — before launching workers,
the launcher must learn which network interfaces are MUTUALLY ROUTABLE
across the hosts (a multi-NIC TPU-VM has management, data and ICI-adjacent
NICs; the first address a hostname resolves to is often wrong). The
reference runs secret-authenticated socket RPC services on every host and
has each task probe the addresses of the next task; interfaces reachable
by the probing peer survive.

TPU-native shape: one small HMAC-authenticated JSON-over-HTTP service per
task host (the same transport family as the rendezvous KV store) with
three verbs — ``addresses`` (list my NICs), ``probe`` (try a TCP connect
from MY network position), ``shutdown``. The driver collects registrations
and runs the ring probe.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import socket
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib import request as urlrequest


def get_local_addresses() -> Dict[str, str]:
    """Enumerate this host's (interface, IPv4) pairs — the reference walks
    psutil.net_if_addrs; here via SIOCGIFADDR so no extra dependency."""
    import fcntl
    out: Dict[str, str] = {}
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        for _, name in socket.if_nameindex():
            try:
                packed = fcntl.ioctl(
                    s.fileno(), 0x8915,  # SIOCGIFADDR
                    struct.pack("256s", name.encode()[:15]))
                out[name] = socket.inet_ntoa(packed[20:24])
            except OSError:
                continue  # interface without an IPv4 address
    finally:
        s.close()
    return out


def _sign(secret: bytes, body: bytes) -> str:
    return hmac.new(secret, body, hashlib.sha256).hexdigest()


class _TaskHandler(BaseHTTPRequestHandler):
    service: "TaskService"

    def log_message(self, fmt, *args):  # silence
        pass

    def _reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(n)
        # secret-authenticated (reference: the launcher-generated secret
        # signs every service message)
        if not hmac.compare_digest(
                self.headers.get("X-Hvd-Auth", ""),
                _sign(self.service._secret, body)):
            self._reply(403, {"error": "bad signature"})
            return
        req = json.loads(body or b"{}")
        verb = self.path.strip("/")
        if verb == "addresses":
            self._reply(200, {"index": self.service.index,
                              "addresses": self.service.addresses()})
        elif verb == "probe":
            ok = self.service.probe(req["addr"], int(req["port"]),
                                    float(req.get("timeout", 2.0)))
            self._reply(200, {"ok": ok})
        elif verb == "shutdown":
            self._reply(200, {"ok": True})
            threading.Thread(target=self.service.stop, daemon=True).start()
        else:
            self._reply(404, {"error": f"unknown verb {verb}"})


class TaskService:
    """Per-host bootstrap service (reference: ``BasicTaskService``).

    ``addresses_override`` lets tests inject fake NIC tables.
    """

    def __init__(self, index: int, secret: bytes, port: int = 0,
                 addresses_override: Optional[Dict[str, str]] = None
                 ) -> None:
        self.index = index
        self._secret = secret
        self._addresses = addresses_override
        handler = type("Handler", (_TaskHandler,), {"service": self})
        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "TaskService":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()

    def addresses(self) -> Dict[str, str]:
        return self._addresses if self._addresses is not None \
            else get_local_addresses()

    def probe(self, addr: str, port: int, timeout: float = 2.0) -> bool:
        """Attempt a TCP connect FROM THIS HOST's network position."""
        try:
            with socket.create_connection((addr, port), timeout=timeout):
                return True
        except OSError:
            return False


def _call(addr: str, port: int, secret: bytes, verb: str,
          payload: dict, timeout: float = 10.0) -> dict:
    body = json.dumps(payload).encode()
    req = urlrequest.Request(
        f"http://{addr}:{port}/{verb}", data=body,
        headers={"X-Hvd-Auth": _sign(secret, body)})
    with urlrequest.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


class TaskClient:
    """Driver-side handle to one task service."""

    def __init__(self, addr: str, port: int, secret: bytes) -> None:
        self.addr = addr
        self.port = port
        self._secret = secret

    def addresses(self) -> Dict[str, str]:
        return _call(self.addr, self.port, self._secret, "addresses",
                     {})["addresses"]

    def probe(self, addr: str, port: int, timeout: float = 2.0) -> bool:
        return _call(self.addr, self.port, self._secret, "probe",
                     {"addr": addr, "port": port,
                      "timeout": timeout})["ok"]

    def shutdown(self) -> None:
        try:
            _call(self.addr, self.port, self._secret, "shutdown", {},
                  timeout=2.0)
        except OSError:
            pass


def find_routable_interfaces(
        tasks: List[TaskClient],
        restrict: Optional[List[str]] = None
) -> List[Tuple[int, Dict[str, str]]]:
    """All-peers probe (reference: ``_run_probe`` +
    ``get_common_interfaces``, ``driver/driver_service.py:49-235``): every
    OTHER task tries to reach each candidate address of task i; an
    interface survives only if every peer can connect. The full check
    (not just a ring) because the TCP core builds a FULL mesh — a NIC one
    peer can't reach would wedge rendezvous for exactly that peer.

    ``restrict``: user-provided interface allowlist (reference: --nics).
    """
    n = len(tasks)
    tables = [t.addresses() for t in tasks]
    if restrict:
        tables = [{k: v for k, v in tab.items() if k in restrict}
                  for tab in tables]
    # All (prober, candidate) pairs are independent — fan out in threads so
    # dead candidates cost one connect timeout total, not one per pair
    # (the reference driver probes concurrently too).
    jobs: List[Tuple[int, str, TaskClient, str, int]] = []
    for i, tab in enumerate(tables):
        for j, prober in enumerate(tasks):
            if j == i:
                continue
            for iface, ip in tab.items():
                jobs.append((i, iface, prober, ip, tasks[i].port))
    results: Dict[Tuple[int, str], bool] = {
        (i, iface): True for i, tab in enumerate(tables) for iface in tab}
    lock = threading.Lock()

    def run_job(job):
        i, iface, prober, ip, port = job
        ok = prober.probe(ip, port)
        if not ok:
            with lock:
                results[(i, iface)] = False

    threads = [threading.Thread(target=run_job, args=(j,), daemon=True)
               for j in jobs]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)

    out: List[Tuple[int, Dict[str, str]]] = []
    for i, tab in enumerate(tables):
        alive = {iface: ip for iface, ip in tab.items()
                 if results[(i, iface)]}
        if not alive:
            raise RuntimeError(
                f"no mutually-routable interface found for task {i} "
                f"(candidates: {tab}); pass an explicit interface list")
        out.append((i, alive))
    return out


def pick_rendezvous_address(routable: List[Tuple[int, Dict[str, str]]]
                            ) -> str:
    """Choose the coordinator address every worker can reach: task 0's
    first surviving interface (reference: the driver's common-interface
    pick feeding HOROVOD_GLOO_RENDEZVOUS_ADDR)."""
    idx, table = routable[0]
    # deterministic order: prefer non-loopback
    for iface in sorted(table):
        if not table[iface].startswith("127."):
            return table[iface]
    return next(iter(table.values()))
