"""TPU pod-slice host discovery from the GCE metadata service.

The TPU-native replacement for the reference's scheduler-environment
detection (``horovod/runner/launch.py:677-709`` MPI/LSF probing,
``horovod/runner/util/lsf.py`` jsrun cluster enumeration): on a Cloud TPU
VM every worker can enumerate the whole pod slice from the instance
metadata server, so ``hvdrun --tpu`` and elastic ``TpuPodDiscovery`` need
no hand-written ``-H`` host list.

Metadata facts (public GCP/Cloud-TPU surface, the same one jax's
``cloud_tpu_cluster`` bootstraps from):
- server: ``http://metadata.google.internal/computeMetadata/v1/``,
  requests must carry ``Metadata-Flavor: Google``;
- ``instance/attributes/worker-network-endpoints``: comma-separated
  entries, one per pod-slice worker, with the worker's internal IP as the
  last ``:``-field;
- ``instance/attributes/agent-worker-number``: this VM's worker index;
- ``instance/attributes/accelerator-type``: e.g. ``v5litepod-16``.

``HVD_TPU_METADATA_ENDPOINT`` overrides the server base URL (unit tests
point it at a local fake; nothing else should).
"""

from __future__ import annotations

import os
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from horovod_tpu.runner.hosts import HostInfo

DEFAULT_ENDPOINT = "http://metadata.google.internal"
_ATTR_BASE = "/computeMetadata/v1/instance/attributes/"
_INSTANCE_BASE = "/computeMetadata/v1/instance/"

#: ``instance/maintenance-event`` value meaning "nothing scheduled";
#: anything else (``TERMINATE_ON_HOST_MAINTENANCE``, ``MIGRATE_ON_...``)
#: is an advance notice that this host is doomed.
MAINTENANCE_NONE = "NONE"


def _endpoint(endpoint: Optional[str]) -> str:
    return (endpoint or os.environ.get("HVD_TPU_METADATA_ENDPOINT")
            or DEFAULT_ENDPOINT).rstrip("/")


def metadata_get(attribute: str, endpoint: Optional[str] = None,
                 timeout: float = 5.0, attempts: int = 3,
                 base: str = _ATTR_BASE) -> str:
    """Fetch one instance attribute; raises ``OSError`` when not on a TPU
    VM (no metadata server) or the attribute is absent.

    Transient failures (connection resets from a briefly-restarting
    metadata server) are retried up to ``attempts`` times under a total
    deadline of ``attempts * timeout`` (:mod:`horovod_tpu.common.retry`);
    an HTTP error (absent attribute) or a non-HTTP answerer (captive
    portal) gives up immediately — patience will not change those."""
    import http.client

    from horovod_tpu.common.retry import retry_call

    req = urllib.request.Request(
        _endpoint(endpoint) + base + attribute,
        headers={"Metadata-Flavor": "Google"})

    def do():
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode().strip()

    try:
        return retry_call(
            do, site="tpu_discovery",
            retry_on=(urllib.error.URLError, TimeoutError, OSError),
            # absent attribute (HTTPError) and non-HTTP answerers —
            # captive portals raising BadStatusLine/UnicodeDecodeError —
            # are permanent for this probe: fail immediately, as before
            give_up_on=(urllib.error.HTTPError,
                        http.client.HTTPException, UnicodeDecodeError),
            attempts=attempts, base_delay_s=0.1, max_delay_s=1.0,
            deadline_s=attempts * timeout)
    except (urllib.error.URLError, urllib.error.HTTPError,
            http.client.HTTPException, UnicodeDecodeError, OSError) as e:
        # the contract stays "OSError when not on a TPU VM"
        raise OSError(f"metadata attribute {attribute!r} unavailable: {e}") \
            from e


def tpu_pod_hosts(slots: int = 1, endpoint: Optional[str] = None) -> \
        List[HostInfo]:
    """All pod-slice worker VMs, in worker order. ``slots`` is processes
    per host — 1 by design (one worker process drives all local chips)."""
    raw = metadata_get("worker-network-endpoints", endpoint)
    hosts = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        # entry fields: <uuid>:<worker-name>:<ip>; be liberal and take the
        # last field so single-field test/bare-IP entries also work
        hosts.append(HostInfo(entry.rsplit(":", 1)[-1], slots))
    if not hosts:
        raise OSError("worker-network-endpoints was empty")
    return hosts


def tpu_worker_index(endpoint: Optional[str] = None) -> int:
    """This VM's worker number within the slice."""
    return int(metadata_get("agent-worker-number", endpoint))


def tpu_accelerator_type(endpoint: Optional[str] = None) -> str:
    return metadata_get("accelerator-type", endpoint)


def tpu_maintenance_event(endpoint: Optional[str] = None,
                          timeout: float = 2.0) -> str:
    """``instance/maintenance-event`` — the advance preemption /
    maintenance notice (GCE surface; ``NONE`` when nothing is scheduled,
    ``TERMINATE_ON_HOST_MAINTENANCE`` when the host is doomed).  The
    PreemptionWatcher (:mod:`horovod_tpu.elastic.preemption`) polls this
    to drive a *planned* elastic drain instead of waiting for the host
    to die.  Raises ``OSError`` off-TPU like every other probe here —
    the watcher latches metadata polling off after repeated failures."""
    return metadata_get("maintenance-event", endpoint, timeout=timeout,
                        attempts=1, base=_INSTANCE_BASE)


def running_on_tpu_vm(endpoint: Optional[str] = None,
                      timeout: float = 1.0) -> bool:
    """Cheap probe: is the TPU metadata surface reachable from here?"""
    try:
        # attempts=1: the probe's point is to be cheap off-TPU, where
        # every attempt burns the full connect timeout
        metadata_get("worker-network-endpoints", endpoint, timeout=timeout,
                     attempts=1)
        return True
    except OSError:
        return False


class TpuPodDiscovery:
    """Elastic host discovery backed by the metadata server (drop-in for
    ``HostDiscoveryScript`` in ``runner/elastic/discovery.py``). Each
    refresh re-reads the slice membership, so repaired/replaced worker VMs
    show up without a user discovery script; dead-but-listed workers are
    handled by the driver's blacklist like any other failed host."""

    def __init__(self, slots: int = 1, endpoint: Optional[str] = None):
        self._slots = slots
        self._endpoint = endpoint

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return {h.hostname: h.slots
                for h in tpu_pod_hosts(self._slots, self._endpoint)}
