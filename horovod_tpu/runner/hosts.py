"""Host list parsing and slot assignment.

Reference: ``horovod/runner/common/util/hosts.py`` (``parse_hosts``,
``SlotInfo`` at :34, ``get_host_assignments`` at :100). On TPU a "slot" is a
host-process driving that host's chips rather than a single GPU.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int

    @classmethod
    def from_string(cls, s: str) -> "HostInfo":
        if ":" in s:
            host, slots = s.rsplit(":", 1)
            return cls(host, int(slots))
        return cls(s, 1)


@dataclasses.dataclass
class SlotInfo:
    """Reference: ``SlotInfo`` (``hosts.py:34``)."""
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int

    def to_env(self) -> Dict[str, str]:
        """Env injected per worker (reference: ``gloo_run.py:65-76``)."""
        return {
            "HOROVOD_HOSTNAME": self.hostname,
            "HOROVOD_RANK": str(self.rank),
            "HOROVOD_SIZE": str(self.size),
            "HOROVOD_LOCAL_RANK": str(self.local_rank),
            "HOROVOD_LOCAL_SIZE": str(self.local_size),
            "HOROVOD_CROSS_RANK": str(self.cross_rank),
            "HOROVOD_CROSS_SIZE": str(self.cross_size),
        }


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """``"h1:4,h2:4"`` → HostInfo list (reference: ``parse_hosts``)."""
    return [HostInfo.from_string(s) for s in hosts_string.split(",") if s]


def parse_hostfile(path: str) -> List[HostInfo]:
    """Hostfile lines ``hostname slots=N`` (reference: hostfile support in
    ``launch.py``)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p[len("slots="):])
            out.append(HostInfo(parts[0], slots))
    return out


def get_host_assignments(hosts: List[HostInfo], np: int,
                         min_np: int = None) -> List[SlotInfo]:
    """Assign np ranks over hosts in order (reference:
    ``get_host_assignments``, ``hosts.py:100``): ranks fill hosts
    sequentially; local/cross ranks derived."""
    total = sum(h.slots for h in hosts)
    if total < np:
        raise ValueError(
            f"Requested np={np} but hosts supply only {total} slots")
    slots: List[SlotInfo] = []
    rank = 0
    cross_size: Dict[int, int] = {}
    for cross_idx, h in enumerate(hosts):
        for local in range(h.slots):
            if rank >= np:
                break
            slots.append(SlotInfo(h.hostname, rank, local, 0, np, 0, 0))
            cross_size[local] = cross_size.get(local, 0) + 1
            rank += 1
    # fill local_size / cross ranks. cross_rank is this host's ordinal among
    # the hosts that HAVE this local_rank (reference semantics: the "cross"
    # communicator groups same-local_rank processes across hosts), so with
    # ragged slot counts cross_rank stays < cross_size.
    per_host: Dict[str, int] = {}
    for s in slots:
        per_host[s.hostname] = per_host.get(s.hostname, 0) + 1
    host_order: List[str] = []
    for s in slots:
        if s.hostname not in host_order:
            host_order.append(s.hostname)
    hosts_with_local: Dict[int, List[str]] = {}
    for s in slots:
        hosts_with_local.setdefault(s.local_rank, [])
        if s.hostname not in hosts_with_local[s.local_rank]:
            hosts_with_local[s.local_rank].append(s.hostname)
    for s in slots:
        s.local_size = per_host[s.hostname]
        s.cross_rank = hosts_with_local[s.local_rank].index(s.hostname)
        s.cross_size = cross_size.get(s.local_rank, 0)
    return slots
