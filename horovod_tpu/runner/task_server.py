"""Remote task-service entry point: the driver launches this on each host
(over ssh) before starting workers, then probes routability through it
(reference: the task-service bootstrap in
``horovod/runner/driver/driver_service.py`` /
``common/service/task_service.py``).

Prints ``HVD_TASK_PORT=<port>`` so the driver learns the bound port over
the ssh pipe; the shared secret arrives via HVD_TPU_SERVICE_SECRET (hex).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main() -> None:
    from horovod_tpu.runner.service import TaskService
    p = argparse.ArgumentParser()
    p.add_argument("--index", type=int, required=True)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--ttl", type=float, default=300.0,
                   help="self-destruct if the driver never shuts us down")
    args = p.parse_args()
    # The secret arrives over STDIN (the ssh channel) so it never appears
    # on a command line or in the remote process table; the env var is a
    # local-testing fallback only.
    secret_hex = os.environ.get("HVD_TPU_SERVICE_SECRET", "")
    if not secret_hex:
        secret_hex = sys.stdin.readline().strip()
    secret = bytes.fromhex(secret_hex)
    svc = TaskService(args.index, secret, port=args.port).start()
    print(f"HVD_TASK_PORT={svc.port}", flush=True)
    deadline = time.monotonic() + args.ttl
    while time.monotonic() < deadline:
        if not svc._thread.is_alive():
            return  # driver called shutdown
        time.sleep(0.2)


if __name__ == "__main__":
    sys.exit(main())
