"""Tree fan-in relay for the driver's control-plane KV.

ROADMAP item 5: the rank-0 HTTP KV is an O(world) single point — every
worker's world polls, world-doc pushes and notification registrations
land on one server, exactly the coordinator bottleneck 1802.05799's
design is criticized for and 1909.09756 shows must become hierarchical
at pod scale.  This module arranges the workers into the same
complete-``arity``-ary tree the fleet metrics plane uses (PR 7:
``parent(r) = (r-1) // arity``), and routes each worker's KV traffic to
its PARENT's relay node instead of the root:

* **world polls** (``GET world/current``) are served from the parent's
  cache — the parent refreshes from ITS upstream at most once per
  ``HVD_TPU_KV_RELAY_TTL_S`` regardless of how many children poll, so
  the root sees O(arity) poll sessions, not O(world × poll rate).  The
  driver's push channel is unchanged and makes most polls moot anyway;
  pushed docs land in the relay node's local KV and serve as fresh
  cache.  Staleness is bounded by the TTL and harmless beyond latency:
  world docs are HMAC-signed and generation-checked by every consumer.
* **registrations and drain notices** (``PUT notify/<r>``,
  ``PUT drain/<r>``) are forwarded hop by hop up the tree to the root,
  so the root's PUT sessions come only from its direct children.

The relay NODE is the worker's existing notification listener (its
``KVStoreServer`` upgraded to a :class:`RelayKVServer`); the relay
CLIENT resolves its parent's listener address from the root's
``notify/<parent>`` registration (one bootstrap lookup per generation)
and **falls back to the root** whenever the parent is dead, unresolved,
or mid-registration — a killed relay node costs latency, never a failed
step.  Per-node request counters (``KVStoreServer.request_counts`` /
``hvd_kv_server_requests_total``) make the fan-in provable rather than
asserted.

``HVD_TPU_KV_RELAY_ARITY`` (default 0) enables the relay; 0 keeps the
flat everyone-to-root topology.  Elastic re-meshes rebuild the route:
the client is keyed by (rank, generation, root), so a renumbered worker
re-resolves its new parent on first use.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Tuple

from horovod_tpu.common.safe_metrics import safe_inc as _metric
from horovod_tpu.runner.http_kv import (KVStoreServer, _KVHandler,
                                        _KVServer, kv_get, kv_put)

#: scopes relayed upstream toward the root (worker -> driver traffic);
#: everything else is local to the node (e.g. the driver's world pushes).
#: "action" carries the autopilot's remediation requests (ISSUE 12):
#: finding→action decisions ride the same tree as drain notices.
#: "result" carries each worker's signed completion receipt (docs/
#: ELASTIC.md "Driver failover & takeover") — a takeover driver that
#: adopted running workers classifies their exits from these.
FORWARD_SCOPES = ("notify", "drain", "action", "result")

#: scopes a relay node serves from its TTL cache (driver -> worker
#: traffic).  GETs for any other scope go root-direct: the relay
#: handler has no relay semantics for them, and a parent-local 404
#: would otherwise masquerade as an authoritative miss.
CACHED_SCOPES = ("world",)


def parent_dead_s() -> float:
    """``HVD_TPU_KV_RELAY_DEAD_S``: how long a failed parent stays
    bypassed (root-direct) before it is retried."""
    from horovod_tpu.common.config import env_float
    return max(0.1, env_float("KV_RELAY_DEAD_S", 5.0))


def resolve_ttl_s() -> float:
    """``HVD_TPU_KV_RELAY_RESOLVE_TTL_S``: how long a failed parent
    LOOKUP is cached.  At generation start every worker registers at
    ~the same moment, so early lookups legitimately miss — the negative
    cache keeps that from turning into a lookup-per-request storm, and
    its expiry is when the tree actually forms."""
    from horovod_tpu.common.config import env_float
    return max(0.05, env_float("KV_RELAY_RESOLVE_TTL_S", 10.0))


def relay_arity() -> int:
    from horovod_tpu.common.config import env_int
    return max(0, env_int("KV_RELAY_ARITY", 0))


def relay_ttl_s() -> float:
    from horovod_tpu.common.config import env_float
    return max(0.05, env_float("KV_RELAY_TTL_S", 1.0))


def relay_parent(rank: int, arity: int) -> Optional[int]:
    """This rank's relay parent, or None for a direct root route (rank
    0, unknown rank, or relay disabled)."""
    if arity <= 0 or rank <= 0:
        return None
    return (rank - 1) // arity


class RelayClient:
    """Routes one worker's control-plane KV traffic: parent first, root
    as the always-correct fallback."""

    def __init__(self, rank: int, root_addr: str, root_port: int,
                 arity: Optional[int] = None) -> None:
        self.rank = rank
        self.root_addr = root_addr
        self.root_port = int(root_port)
        self.arity = relay_arity() if arity is None else arity
        self.parent_rank = relay_parent(rank, self.arity)
        self._lock = threading.Lock()
        self._parent_addr: Optional[Tuple[str, int]] = None
        self._parent_dead_until = 0.0
        self._resolve_failed_until = 0.0

    # -- parent resolution --------------------------------------------------
    def _resolve_parent(self, timeout: float) -> Optional[Tuple[str, int]]:
        """The parent's listener address from the root's ``notify``
        scope; one bootstrap lookup per generation, negative results
        cached briefly (the parent may simply not have registered yet)."""
        if self.parent_rank is None:
            return None
        with self._lock:
            if self._parent_addr is not None:
                return self._parent_addr
            if time.monotonic() < self._resolve_failed_until:
                return None
        try:
            raw = kv_get(self.root_addr, self.root_port, "notify",
                         str(self.parent_rank), timeout=timeout,
                         site="kv_relay.resolve", peer="driver")
            if raw:
                host, _, port = raw.decode().rpartition(":")
                addr = (host, int(port))
                with self._lock:
                    self._parent_addr = addr
                return addr
        except (OSError, ValueError, UnicodeDecodeError):
            pass
        with self._lock:
            self._resolve_failed_until = time.monotonic() + resolve_ttl_s()
        return None

    def _parent_usable(self, timeout: float) -> Optional[Tuple[str, int]]:
        with self._lock:
            if time.monotonic() < self._parent_dead_until:
                return None
        return self._resolve_parent(timeout)

    def _mark_parent_dead(self, site: str) -> None:
        with self._lock:
            self._parent_dead_until = time.monotonic() + parent_dead_s()
            self._parent_addr = None  # re-resolve: it may have moved
            self._resolve_failed_until = 0.0
        _metric("hvd_kv_relay_fallback_total",
                "relay-parent failures degraded to a direct root "
                "request, per call site", site=site)

    # -- the client surface -------------------------------------------------
    def get(self, scope: str, key: str, timeout: float = 30.0,
            site: str = "kv_relay.get",
            count_exhausted: bool = True) -> Optional[bytes]:
        addr = self._parent_usable(timeout) \
            if scope in CACHED_SCOPES else None
        if addr is not None:
            try:
                # attempts=1: the root fallback IS the retry — a dead
                # parent must cost one timeout, not a full retry cycle
                # longer than its own bypass window
                return kv_get(addr[0], addr[1], scope, key,
                              timeout=timeout, site=site,
                              peer=self.parent_rank, attempts=1)
            except OSError:
                self._mark_parent_dead(site)
        return kv_get(self.root_addr, self.root_port, scope, key,
                      timeout=timeout, site=site, peer="driver",
                      count_exhausted=count_exhausted)

    def put(self, scope: str, key: str, value: bytes,
            timeout: float = 30.0, site: str = "kv_relay.put",
            count_exhausted: bool = True) -> None:
        addr = self._parent_usable(timeout) \
            if scope in FORWARD_SCOPES else None
        if addr is not None:
            try:
                kv_put(addr[0], addr[1], scope, key, value,
                       timeout=timeout, site=site,
                       peer=self.parent_rank, attempts=1)
                return
            except OSError:
                self._mark_parent_dead(site)
        kv_put(self.root_addr, self.root_port, scope, key, value,
               timeout=timeout, site=site, peer="driver",
               count_exhausted=count_exhausted)


# -- relay node (server side) -------------------------------------------------
class _RelayHandler(_KVHandler):
    """The listener's KV handler with relay behavior: stale ``world``
    reads refresh from upstream (bounded by the TTL, so N polling
    children cost one upstream fetch per TTL), and PUTs to the forwarded
    scopes travel up the tree toward the root."""

    def do_GET(self):
        scope, key = self._split()
        srv = self.server
        if scope not in CACHED_SCOPES:
            # one source of truth with RelayClient.get's routing: a
            # scope the client would relay must have relay semantics
            # here, or a parent-local 404 would masquerade as an
            # authoritative miss
            return super().do_GET()
        srv.note_request("GET", scope)
        _metric("hvd_kv_relay_requests_total",
                "KV requests served by this relay node, per scope",
                scope=scope)
        def read_cache():
            with srv.kv_lock:
                return (srv.kv.get(scope, {}).get(key),
                        srv.fresh.get((scope, key), 0.0)
                        > time.monotonic() - relay_ttl_s())

        val, fresh = read_cache()
        if val is None or not fresh:
            # single-flight refresh: children poll in lockstep (commits
            # synchronize on the collective), so after a TTL expiry ALL
            # of them observe stale — without this gate each would fire
            # its own upstream fetch and the per-TTL fan-in bound would
            # quietly become per-child.  Waiters re-read what the
            # holder fetched.
            with srv.refresh_lock:
                val, fresh = read_cache()
                upstream = srv.upstream() \
                    if (val is None or not fresh) else None
                if upstream is not None:
                    try:
                        _metric("hvd_kv_relay_upstream_total",
                                "relay-node refreshes/forwards sent "
                                "upstream, per op", op="get")
                        got = upstream.get(scope, key, timeout=5.0,
                                           site="kv_relay.refresh")
                        if got is not None:
                            val = got
                        with srv.kv_lock:
                            if got is not None:
                                srv.kv.setdefault(scope, {})[key] = got
                            # a clean upstream 404 is also knowledge:
                            # don't re-ask for every child until the
                            # TTL passes
                            srv.fresh[(scope, key)] = time.monotonic()
                    except OSError:
                        # upstream dark: serve the stale copy if we have
                        # one (docs are generation-checked; stale =
                        # latency, not corruption), else tell the child
                        # to go to the root
                        if val is None:
                            self.send_response(503)
                            self.end_headers()
                            return
                        with srv.kv_lock:
                            # the failure also refreshes the stamp:
                            # a dark root costs ONE upstream attempt
                            # per TTL per node, not one per child
                            # (whose polls would otherwise pile up
                            # behind the refresh lock, time out, and
                            # hammer the dark root directly)
                            srv.fresh[(scope, key)] = time.monotonic()
        if val is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_PUT(self):
        scope, key = self._split()
        srv = self.server
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        srv.note_request("PUT", scope)
        if scope in FORWARD_SCOPES:
            _metric("hvd_kv_relay_requests_total",
                    "KV requests served by this relay node, per scope",
                    scope=scope)
            upstream = srv.upstream()
            if upstream is None:
                self.send_response(503)  # child falls back to the root
                self.end_headers()
                return
            # causal tracing: the publisher's context arrives as the
            # traceparent header; each relay hop re-stamps a CHILD span
            # and forwards under it, so the merged tree shows the doc's
            # path up the tree hop by hop
            from horovod_tpu import tracing
            fwd_ctx = tracing.child(
                tracing.decode(self.headers.get(tracing.TRACEPARENT)),
                "kv")
            t0 = time.monotonic()
            try:
                _metric("hvd_kv_relay_upstream_total",
                        "relay-node refreshes/forwards sent upstream, "
                        "per op", op="put")
                with tracing.activate(fwd_ctx):
                    upstream.put(scope, key, body, timeout=5.0,
                                 site="kv_relay.forward")
            except OSError:
                self.send_response(503)
                self.end_headers()
                return
            finally:
                tracing.record_span("kv", "relay_forward", fwd_ctx,
                                    dur_s=time.monotonic() - t0,
                                    scope=scope, key=key)
            self.send_response(200)
            self.end_headers()
            return
        with srv.kv_lock:
            srv.kv.setdefault(scope, {})[key] = body
            # a direct PUT (the driver's world push) is fresh truth
            srv.fresh[(scope, key)] = time.monotonic()
        self.send_response(200)
        self.end_headers()


class RelayKVServer(KVStoreServer):
    """A notification listener that is also a relay node.

    ``upstream_fn`` returns the RelayClient routing THIS worker's own
    traffic (parent-or-root) — children's requests recurse up the same
    tree the client descends."""

    def __init__(self, upstream_fn, port: int = 0) -> None:
        self._upstream_fn = upstream_fn
        super().__init__(port=port)
        self._httpd.fresh = {}
        self._httpd.refresh_lock = threading.Lock()
        self._httpd.upstream = self._upstream

    def _make_server(self, port: int):
        return _KVServer(("0.0.0.0", port), _RelayHandler)

    def _upstream(self) -> Optional[RelayClient]:
        try:
            return self._upstream_fn()
        except Exception:
            return None


def elastic_kv_endpoint() -> Optional[Tuple[str, int]]:
    """The managing elastic driver's KV endpoint from
    ``HVD_ELASTIC_KV`` (``host:port``) — THE one parse of that env
    contract, shared by every worker→driver publisher (drain notices,
    autopilot action requests).  Returns None when no driver manages
    this job; raises :class:`ValueError` on a malformed value so the
    caller can say, in its own words, that this is a config bug and
    not a transient."""
    kv = os.environ.get("HVD_ELASTIC_KV", "")
    if not kv:
        return None
    addr, _, port = kv.rpartition(":")
    try:
        return addr, int(port)
    except ValueError:
        raise ValueError(f"malformed HVD_ELASTIC_KV {kv!r}") from None


# -- process-wide client ------------------------------------------------------
_client: Optional[RelayClient] = None
_client_key = None
_client_lock = threading.Lock()


def _identity() -> Tuple[int, str]:
    rank = os.environ.get("HOROVOD_RANK",
                          os.environ.get("HVD_TPU_RANK", "0"))
    gen = os.environ.get("HVD_ELASTIC_GENERATION", "0")
    try:
        return int(rank), gen
    except ValueError:
        return 0, gen


def client(root_addr: str, root_port: int) -> RelayClient:
    """The process's relay client for the given root, rebuilt whenever
    the worker's (rank, generation) or the root moves — an elastic
    re-mesh renumbers ranks, and the route must follow."""
    global _client, _client_key
    rank, gen = _identity()
    key = (rank, gen, root_addr, int(root_port), relay_arity())
    with _client_lock:
        if _client is None or _client_key != key:
            _client = RelayClient(rank, root_addr, int(root_port))
            _client_key = key
        return _client


def reset() -> None:
    """Drop the cached route (tests / full shutdown)."""
    global _client, _client_key
    with _client_lock:
        _client = None
        _client_key = None
