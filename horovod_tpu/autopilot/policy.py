"""Declarative finding→remediation policies: schema + validation.

A policy document is JSON (inline in ``HVD_TPU_AUTOPILOT_POLICY`` or a
path to a file — the same inline-or-file convention as the chaos fault
plans) describing WHICH anomaly findings trigger WHICH remediation,
and under what rate limits and gates:

.. code-block:: json

    {
      "policies": [
        {"name": "straggler-drain",
         "finding": "persistent_straggler",
         "action": "drain_and_replace",
         "cooldown_s": 300, "hysteresis": 1,
         "max_actions": 2, "window_s": 3600,
         "horizon_steps": 500, "max_remesh_p50_s": 0}
      ]
    }

Policy fields:

* ``name`` (required) — unique policy id; every decision is recorded
  under it (metrics label, flight event, action log).
* ``finding`` (required) — the anomaly finding ``kind`` this policy
  subscribes to.  Both the engine's native step/fleet detectors and
  external ``report_finding()`` detectors take the same path.
* ``action`` (required) — one of the :data:`ACTIONS` catalog below.
* ``cooldown_s`` — after a fired (or dry-run) decision, further
  findings are suppressed for this long (default 300).
* ``hysteresis`` — consecutive matching findings required before the
  policy may fire (default 1; the recompile-storm policy uses 2 —
  one storm report is noise, a repeat on the same function is a bug).
* ``max_actions`` / ``window_s`` — at most ``max_actions`` fired/dry-run
  decisions per sliding ``window_s`` seconds (defaults 2 / 3600);
  beyond it decisions are suppressed with reason ``budget``.
* ``key_field`` — optional finding field name scoping hysteresis,
  cooldown and budget PER distinct value (the recompile-storm policy
  keys on ``function``: storms on two different functions are two
  independent decision streams).
* action parameters — ``horizon_steps`` + ``max_remesh_p50_s``
  (``drain_and_replace``: the SLO gate projects the straggler's loss
  over ``horizon_steps`` and refuses a re-mesh whose measured p50 cost
  exceeds it; ``max_remesh_p50_s`` > 0 additionally caps the
  acceptable p50 outright), ``max_margin_frac``
  (``commit_restart``: fire only when the fleet OOM margin has fallen
  below this fraction of the device limit).

Validation is strict — a typo'd field or an unknown action is a config
error surfaced when the engine arms, not a silently dead policy.

``HVD_TPU_AUTOPILOT`` ∈ {``off``, ``observe``, ``act``} selects the
mode (default ``observe``): ``observe`` evaluates every gate and
records the identical decision stream ``act`` would, without acting —
the audit trail IS the dry run.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Union

#: remediation catalog: action name -> True when the remediation needs
#: the elastic driver (it is requested over the KV ``action/`` scope;
#: without a driver the decision is recorded and the dispatch skipped)
ACTIONS: Dict[str, bool] = {
    "drain_and_replace": True,   # plan the world around a sick worker
    "commit_restart": True,      # final durable commit + planned restart
    "freeze_alert": False,       # name the offender, stop the bleeding
    "retune": False,             # invalidate plan cache + re-search
    # ISSUE 13 (data-plane integrity, docs/OBSERVABILITY.md "Autopilot"):
    "quarantine_rank": True,     # drain the SDC-divergent rank AND
    #                              blocklist its host with evidence —
    #                              unlike a preemption drain, the exit
    #                              is held against the hardware
    "rollback_restore": False,   # persistent grad_nonfinite: restore
    #                              the last durable checkpoint via the
    #                              registered rollback hooks instead of
    #                              committing a poisoned state forward
    # ISSUE 14 (zero-drop serving, docs/SERVING.md):
    "scale_out": False,          # serving slo_breach: raise the replica
    #                              fleet's target size via the
    #                              registered scale-out hooks (the
    #                              ReplicaFleet wires itself in)
    # ISSUE 18 (canary weight rollout, docs/SERVING.md "Canary
    # rollout"): both subscribe to the SAME rollout_verdict finding;
    # the engine's SLO gate passes each policy only when the verdict
    # matches its action, so one comparator report drives exactly one
    # of the two transitions
    "promote_rollout": False,    # verdict "promote": advance the
    #                              canary stage (N% → 50% → fleet-wide)
    #                              via the registered rollout hooks
    "rollback_rollout": False,   # verdict "rollback": repin every
    #                              canary replica to the incumbent
    #                              version — the same atomic
    #                              between-batch flip as a hot swap,
    #                              so zero requests fail
}

MODES = ("off", "observe", "act")

DEFAULT_COOLDOWN_S = 300.0
DEFAULT_MAX_ACTIONS = 2
DEFAULT_WINDOW_S = 3600.0
DEFAULT_HORIZON_STEPS = 500
DEFAULT_MAX_MARGIN_FRAC = 0.1


class AutopilotError(ValueError):
    """An autopilot policy document failed validation."""


@dataclasses.dataclass
class Policy:
    name: str
    finding: str
    action: str
    cooldown_s: float = DEFAULT_COOLDOWN_S
    hysteresis: int = 1
    max_actions: int = DEFAULT_MAX_ACTIONS
    window_s: float = DEFAULT_WINDOW_S
    key_field: Optional[str] = None
    # drain_and_replace SLO gate
    horizon_steps: int = DEFAULT_HORIZON_STEPS
    max_remesh_p50_s: float = 0.0        # 0 = no absolute cap
    # commit_restart SLO gate
    max_margin_frac: float = DEFAULT_MAX_MARGIN_FRAC

    def needs_driver(self) -> bool:
        return ACTIONS[self.action]


_POLICY_KEYS = {"name", "finding", "action", "cooldown_s", "hysteresis",
                "max_actions", "window_s", "key_field", "horizon_steps",
                "max_remesh_p50_s", "max_margin_frac"}


def _parse_policy(doc: Dict[str, Any], index: int) -> Policy:
    if not isinstance(doc, dict):
        raise AutopilotError(f"policy #{index}: not an object: {doc!r}")
    unknown = set(doc) - _POLICY_KEYS
    if unknown:
        raise AutopilotError(
            f"policy #{index}: unknown keys {sorted(unknown)}")
    for key in ("name", "finding", "action"):
        v = doc.get(key)
        if not isinstance(v, str) or not v:
            raise AutopilotError(
                f"policy #{index}: {key!r} must be a non-empty string")
    action = doc["action"]
    if action not in ACTIONS:
        raise AutopilotError(
            f"policy #{index}: unknown action {action!r} "
            f"(known: {sorted(ACTIONS)})")
    key_field = doc.get("key_field")
    if key_field is not None and (not isinstance(key_field, str)
                                  or not key_field):
        raise AutopilotError(
            f"policy #{index}: 'key_field' must be a non-empty string")
    try:
        cooldown_s = float(doc.get("cooldown_s", DEFAULT_COOLDOWN_S))
        hysteresis = int(doc.get("hysteresis", 1))
        max_actions = int(doc.get("max_actions", DEFAULT_MAX_ACTIONS))
        window_s = float(doc.get("window_s", DEFAULT_WINDOW_S))
        horizon_steps = int(doc.get("horizon_steps",
                                    DEFAULT_HORIZON_STEPS))
        max_remesh_p50_s = float(doc.get("max_remesh_p50_s", 0.0))
        max_margin_frac = float(doc.get("max_margin_frac",
                                        DEFAULT_MAX_MARGIN_FRAC))
    except (TypeError, ValueError) as e:
        raise AutopilotError(
            f"policy #{index}: bad field value: {e}") from None
    if cooldown_s < 0 or window_s <= 0 or max_remesh_p50_s < 0:
        raise AutopilotError(
            f"policy #{index}: negative cooldown/window/p50 cap")
    if hysteresis < 1:
        raise AutopilotError(
            f"policy #{index}: hysteresis must be >= 1")
    if max_actions < 1:
        # a 0-action policy is a policy that can never fire: config bug
        raise AutopilotError(
            f"policy #{index}: max_actions must be >= 1 (remove the "
            "policy, or run HVD_TPU_AUTOPILOT=observe, to disable it)")
    if horizon_steps < 1:
        raise AutopilotError(
            f"policy #{index}: horizon_steps must be >= 1")
    if not (0.0 <= max_margin_frac <= 1.0):
        raise AutopilotError(
            f"policy #{index}: max_margin_frac must be in [0, 1]")
    return Policy(name=doc["name"], finding=doc["finding"], action=action,
                  cooldown_s=cooldown_s, hysteresis=hysteresis,
                  max_actions=max_actions, window_s=window_s,
                  key_field=key_field, horizon_steps=horizon_steps,
                  max_remesh_p50_s=max_remesh_p50_s,
                  max_margin_frac=max_margin_frac)


def parse_policies(doc: Union[str, Dict[str, Any]]) -> List[Policy]:
    """Parse + validate a policy document from a JSON string or an
    already-decoded dict; raises :class:`AutopilotError` on any schema
    violation (including duplicate policy names — decisions are keyed
    by name, two policies sharing one would corrupt the audit trail)."""
    if isinstance(doc, str):
        try:
            doc = json.loads(doc)
        except ValueError as e:
            raise AutopilotError(
                f"autopilot policy document is not valid JSON: {e}") \
                from None
    if not isinstance(doc, dict):
        raise AutopilotError(
            f"autopilot policy document must be an object, got "
            f"{type(doc).__name__}")
    unknown = set(doc) - {"policies"}
    if unknown:
        raise AutopilotError(f"unknown document keys {sorted(unknown)}")
    raw = doc.get("policies", [])
    if not isinstance(raw, list):
        raise AutopilotError("'policies' must be a list")
    policies = [_parse_policy(p, i) for i, p in enumerate(raw)]
    names = [p.name for p in policies]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise AutopilotError(f"duplicate policy names {dupes}")
    return policies


def default_policies() -> List[Policy]:
    """The shipped policy set — the four wired remediations of ISSUE
    12, the two data-plane integrity remediations of ISSUE 13, and the
    serving SLO scale-out of ISSUE 14.  Used when
    ``HVD_TPU_AUTOPILOT_POLICY`` is unset; a custom document REPLACES
    it (policies are explicit, not merged)."""
    return [
        Policy(name="straggler-drain", finding="persistent_straggler",
               action="drain_and_replace"),
        Policy(name="hbm-planned-restart", finding="hbm_growth",
               action="commit_restart"),
        Policy(name="recompile-freeze", finding="recompile_storm",
               action="freeze_alert", hysteresis=2, key_field="function"),
        Policy(name="topology-retune", finding="world_changed",
               action="retune", cooldown_s=60.0),
        # a replica whose canary digest disagrees with the majority is
        # producing silently-wrong math (docs/TROUBLESHOOTING.md "My
        # replicas disagree"): one finding is enough — SDC does not
        # heal, and every step it stays in the allreduce poisons the
        # others' gradients
        Policy(name="replica-quarantine", finding="replica_divergence",
               action="quarantine_rank"),
        # persistent non-finite gradients (the guard's escalation,
        # train/guard.py): the optimizer state may already be poisoned
        # — roll back to the last durable commit rather than carry it
        Policy(name="nonfinite-rollback", finding="grad_nonfinite",
               action="rollback_restore"),
        # serving p99 over SLO for consecutive windows (ISSUE 14,
        # horovod_tpu/serving/metrics.py): more replicas is the
        # remediation the fleet can apply itself; 60s cooldown — a
        # scale-out needs a replica cold-start before it can help,
        # re-firing faster than that just overshoots
        Policy(name="serving-slo-scaleout", finding="slo_breach",
               action="scale_out", cooldown_s=60.0),
        # canary weight rollout (ISSUE 18): the comparator reports one
        # rollout_verdict per evaluation window; the verdict gate
        # routes it to exactly one of these.  Promotion advances
        # through MULTIPLE stages (canary → 50% → fleet-wide) within
        # one rollout, so its cooldown is just hysteresis against a
        # duplicate report and its budget covers every stage; rollback
        # is one-shot per rollout and keeps the conservative defaults
        Policy(name="rollout-promote", finding="rollout_verdict",
               action="promote_rollout", cooldown_s=1.0,
               max_actions=6, window_s=3600.0),
        Policy(name="rollout-rollback", finding="rollout_verdict",
               action="rollback_rollout", cooldown_s=60.0),
    ]


def load_policies_from_env() -> List[Policy]:
    """The policy set named by ``HVD_TPU_AUTOPILOT_POLICY`` (inline JSON
    when the value starts with ``{``, else a file path); the default
    set when unset."""
    raw = os.environ.get("HVD_TPU_AUTOPILOT_POLICY", "").strip()
    if not raw:
        return default_policies()
    if not raw.startswith("{"):
        try:
            with open(raw) as f:
                raw = f.read()
        except OSError as e:
            raise AutopilotError(
                f"HVD_TPU_AUTOPILOT_POLICY names an unreadable file: {e}"
            ) from None
    return parse_policies(raw)


def mode() -> str:
    """``HVD_TPU_AUTOPILOT`` ∈ {off, observe, act}; default observe.
    An unknown value degrades to ``observe`` with a warning — the safe
    mode records everything and touches nothing."""
    from horovod_tpu.common.config import env_str
    m = env_str("AUTOPILOT", "observe").strip().lower()
    if m not in MODES:
        try:
            from horovod_tpu.common.logging import get_logger
            get_logger().warning(
                "HVD_TPU_AUTOPILOT=%r is not one of %s; running in "
                "'observe'", m, MODES)
        except Exception:
            pass
        return "observe"
    return m
