"""Fleet autopilot: finding→remediation policies with a full decision
audit trail (ROADMAP item 3; docs/OBSERVABILITY.md "Autopilot").

The observability plane *detects* (anomaly engine, recompile storms,
HBM slow leaks, persistent stragglers, the measured re-mesh SLO) and
the control plane can *act* (proactive drain, plan-cache re-tune,
durable commit, elastic re-mesh) — this package closes the loop the
reference's ParameterManager closed for knobs, at the membership/
placement level: declarative, rate-limited, SLO-gated policies whose
every decision — fired, suppressed, or dry-run — is itself a
first-class observable artifact.

* :mod:`horovod_tpu.autopilot.policy` — the JSON policy spec
  (``HVD_TPU_AUTOPILOT_POLICY`` inline-or-file, strict validation) and
  the ``HVD_TPU_AUTOPILOT`` mode knob (off / observe / act; observe —
  record everything, touch nothing — is the default);
* :mod:`horovod_tpu.autopilot.engine` — the policy engine: hysteresis,
  cooldown, action budgets, SLO gates, and the four-channel audit
  trail (``hvd_autopilot_*`` metrics, ``autopilot_decision`` flight
  events, the ``actions_rank<r>.jsonl`` log behind
  ``python -m horovod_tpu.metrics history --actions``, the autopsy
  summary's ``actions`` section);
* :mod:`horovod_tpu.autopilot.actions` — the wired remediations:
  straggler drain-and-replace and HBM planned restart over the KV
  ``action/`` scope, recompile-storm freeze/alert, topology re-tune.

Subscription is automatic: the anomaly engine routes every finding —
native ``_flag`` detectors and external ``report_finding()`` detectors
alike — through :func:`on_finding`.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

from horovod_tpu.autopilot.policy import (ACTIONS, AutopilotError, MODES,
                                          Policy, default_policies,
                                          load_policies_from_env, mode,
                                          parse_policies)
from horovod_tpu.autopilot.engine import PolicyEngine, remesh_p50_s
from horovod_tpu.autopilot import actions

__all__ = [
    "ACTIONS", "AutopilotError", "MODES", "Policy", "PolicyEngine",
    "parse_policies", "default_policies", "load_policies_from_env",
    "mode", "enabled", "on_finding", "default_engine", "ensure_engine",
    "recent_decisions", "remesh_p50_s", "actions", "reset",
]

_ENGINE: Optional[PolicyEngine] = None
_ENGINE_KEY = None
_LOCK = threading.Lock()


def enabled() -> bool:
    return mode() != "off"


def _env_key() -> tuple:
    return (mode(), os.environ.get("HVD_TPU_AUTOPILOT_POLICY", ""))


def default_engine() -> Optional[PolicyEngine]:
    """The process-wide engine (None when ``HVD_TPU_AUTOPILOT=off``),
    rebuilt when the mode or policy env changes (elastic re-init,
    tests).  A policy document that fails validation here is swallowed
    into None — :func:`ensure_engine` (called from ``hvd.init``) is the
    loud path for config errors."""
    global _ENGINE, _ENGINE_KEY
    if not enabled():
        return None
    key = _env_key()
    eng = _ENGINE
    if eng is not None and _ENGINE_KEY == key:
        # the engine survives elastic re-inits (cooldown/budget state
        # must persist across world changes) but its recorded identity
        # must not go stale when a re-mesh renumbers this worker
        eng.refresh_identity()
        return eng
    with _LOCK:
        if _ENGINE is None or _ENGINE_KEY != key:
            try:
                _ENGINE = PolicyEngine()
                _ENGINE_KEY = key
            except AutopilotError:
                return None
        return _ENGINE


def ensure_engine() -> Optional[PolicyEngine]:
    """Arm the engine, surfacing policy-document errors LOUDLY —
    called from ``hvd.init`` so a typo'd ``HVD_TPU_AUTOPILOT_POLICY``
    fails the job at startup instead of running policy-free
    (the same contract as a typo'd chaos fault plan)."""
    global _ENGINE, _ENGINE_KEY
    if not enabled():
        return None
    key = _env_key()
    with _LOCK:
        if _ENGINE is None or _ENGINE_KEY != key:
            _ENGINE = PolicyEngine()  # AutopilotError propagates
            _ENGINE_KEY = key
        else:
            _ENGINE.refresh_identity()  # re-init may have renumbered us
        return _ENGINE


def on_finding(finding: dict) -> List[dict]:
    """The anomaly engine's fan-out hook: one call per flagged finding
    (cheap None check when the autopilot is off)."""
    eng = default_engine()
    return eng.on_finding(finding) if eng is not None else []


def recent_decisions() -> List[dict]:
    """Decisions so far (empty when the engine never armed) — what the
    autopsy summary embeds under ``actions``."""
    eng = _ENGINE
    return eng.recent_decisions() if eng is not None else []


def reset() -> None:
    """Drop the engine and action-module state so env is re-read
    (tests, elastic re-init)."""
    global _ENGINE, _ENGINE_KEY
    with _LOCK:
        _ENGINE = None
        _ENGINE_KEY = None
    actions.reset()
