"""The wired remediations: what a fired policy actually does.

Two shapes (docs/OBSERVABILITY.md "Autopilot"):

* **Driver actions** (``drain_and_replace``, ``commit_restart``,
  ``quarantine_rank``) travel worker→driver as a JSON request PUT into
  the KV ``action/`` scope — relay-routed up the same tree as drain
  notices (:mod:`horovod_tpu.runner.kv_relay`), consumed by the elastic
  driver's poll loop (``runner/elastic/driver.py``), which plans the
  target worker out of the world through the PR-10 drain plumbing: the
  exit is DRAINED, never FAILURE.
  ``drain_and_replace`` reserves the sick host for the drain cooldown
  (the replacement lands elsewhere when capacity exists);
  ``commit_restart`` leaves the host admitted so the planned restart
  respawns in place immediately — the drain-stamped world doc already
  guarantees the doomed worker's final durable commit is flushed
  before it exits (``elastic.run``'s preemption_drain branch);
  ``quarantine_rank`` (ISSUE 13) is the one planned exit that IS held
  against the hardware — after the drain re-mesh succeeds the driver
  blocklists the divergent rank's host WITH the canary evidence that
  convicted it (silent data corruption is a device property, and a
  replacement landing back on the same chip would diverge again).
* **Local actions** (``freeze_alert``, ``retune``,
  ``rollback_restore``) act in-process: ``freeze_alert`` names the
  offending function loudly and adds it to the frozen set
  (``hvd_autopilot_frozen_functions``); ``retune`` invalidates the
  persistent autotune plan cache
  (:func:`horovod_tpu.train.autotune.invalidate_plan_cache`) and runs
  any registered re-tune hooks in the background, so the next plan
  lookup re-searches against the CURRENT topology;
  ``rollback_restore`` (ISSUE 13) runs the registered rollback hooks
  (:func:`register_rollback_hook`) so a run whose gradients went
  persistently non-finite restores the last durable checkpoint instead
  of committing a poisoned optimizer state forward.

Dispatch always happens on a short-lived daemon thread: the decision
itself is made under the anomaly engine's lock, and a KV round-trip
(or a slow shared filesystem) must never stall detection.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, List, Optional, Set

from horovod_tpu.autopilot.policy import Policy

_lock = threading.Lock()
_seq = 0
_frozen: Set[str] = set()
_retune_hooks: List[Callable[[], None]] = []
_rollback_hooks: List[Callable[[], None]] = []
_scale_out_hooks: List[Callable[[], None]] = []
_promote_rollout_hooks: List[Callable[[dict], None]] = []
_rollback_rollout_hooks: List[Callable[[dict], None]] = []

#: finding fields carried as quarantine EVIDENCE into the driver's
#: blocklist record (docs/OBSERVABILITY.md "Autopilot"): the canary
#: digests that convicted the rank travel with the action, so the
#: audit trail says WHY the host was blocklisted, not just that it was
_EVIDENCE_FIELDS = ("step", "digest", "majority", "world", "consecutive")


def dispatch(policy: Policy, finding: dict, decision: dict) -> None:
    """Run the policy's remediation asynchronously (never raises)."""
    t = threading.Thread(target=_run, args=(policy, finding, decision),
                         name=f"hvd-tpu-autopilot-{policy.action}",
                         daemon=True)
    t.start()


def _run(policy: Policy, finding: dict, decision: dict) -> None:
    try:
        if policy.action == "drain_and_replace":
            _request_driver_action("drain", int(finding["rank"]),
                                   policy, decision)
        elif policy.action == "commit_restart":
            _request_driver_action("restart", _own_rank(),
                                   policy, decision)
        elif policy.action == "quarantine_rank":
            _request_driver_action(
                "quarantine", int(finding["rank"]), policy, decision,
                evidence={k: finding[k] for k in _EVIDENCE_FIELDS
                          if k in finding})
        elif policy.action == "rollback_restore":
            rollback(policy, finding)
        elif policy.action == "scale_out":
            scale_out(policy, finding)
        elif policy.action == "promote_rollout":
            promote_rollout(policy, finding, decision)
        elif policy.action == "rollback_rollout":
            rollback_rollout(policy, finding, decision)
        elif policy.action == "freeze_alert":
            freeze(str(finding.get("function", "unknown")), policy,
                   finding)
        elif policy.action == "retune":
            retune(policy, finding)
    except Exception:
        try:
            from horovod_tpu.common.logging import get_logger
            get_logger().warning("autopilot: %s remediation failed",
                                 policy.action, exc_info=True)
        except Exception:
            pass


def _own_rank() -> int:
    v = os.environ.get("HOROVOD_RANK", os.environ.get("HVD_TPU_RANK",
                                                      "0"))
    try:
        return int(v)
    except ValueError:
        return 0


def _flight(kind: str, **fields) -> None:
    try:
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event(kind, **fields)
    except Exception:
        pass


# -- driver actions (the KV ``action/`` scope) --------------------------------
def _request_driver_action(kind: str, target_rank: int, policy: Policy,
                           decision: dict, evidence=None) -> bool:
    """PUT the action request at the elastic driver's KV, relay-routed.
    Returns False (with the evidence recorded) when no driver manages
    this job — a standalone run's decision is still a first-class audit
    artifact, it just has nobody to drain for it."""
    global _seq
    from horovod_tpu.runner import kv_relay
    try:
        endpoint = kv_relay.elastic_kv_endpoint()
    except ValueError as e:
        from horovod_tpu.common.logging import get_logger
        get_logger().warning(
            "autopilot: %s; %s for rank %d dropped", e, kind,
            target_rank)
        return False
    if endpoint is None:
        from horovod_tpu.common.logging import get_logger
        get_logger().warning(
            "autopilot: %s for rank %d has nowhere to go: no elastic "
            "driver KV (HVD_ELASTIC_KV)", kind, target_rank)
        _flight("autopilot_action_unroutable", action=kind,
                target_rank=target_rank, policy=policy.name)
        return False
    addr, port_i = endpoint
    with _lock:
        _seq += 1
        seq = _seq
    body = {
        "action": kind,
        "rank": int(target_rank),
        "policy": policy.name,
        "finding": decision.get("finding"),
        "source": "autopilot",
        "from_rank": _own_rank(),
        "generation": int(os.environ.get("HVD_ELASTIC_GENERATION", "0")),
        "at": time.time()}
    if evidence:
        body["evidence"] = evidence
    # causal tracing: the action doc continues the decision's trace
    # (which continued the finding's) — the driver childs from the
    # embedded traceparent when it handles the request, so finding →
    # decision → action → drain → re-mesh share ONE trace id
    from horovod_tpu import tracing
    actx = tracing.child(
        tracing.decode(decision.get(tracing.TRACEPARENT)), "autopilot")
    if actx is not None:
        body[tracing.TRACEPARENT] = actx.traceparent
    doc = json.dumps(body).encode()
    with tracing.activate(actx):
        kv_relay.client(addr, port_i).put(
            "action", f"{_own_rank()}-{seq}", doc, timeout=5.0,
            site="autopilot.action")
        _flight("autopilot_action_published", action=kind,
                target_rank=target_rank, policy=policy.name)
    return True


# -- local actions ------------------------------------------------------------
def freeze(function: str, policy: Optional[Policy] = None,
           finding: Optional[dict] = None) -> None:
    """Repeated recompile storms on one function: name it LOUDLY and
    add it to the frozen set.  The alert is the remediation — shape
    drift is a code bug only the owner can fix; what the autopilot can
    do is make sure the function's NAME reaches the operator through
    every channel instead of dying as compiler mush."""
    with _lock:
        _frozen.add(function)
        n = len(_frozen)
    try:
        from horovod_tpu.metrics.registry import default_registry
        default_registry().gauge(
            "hvd_autopilot_frozen_functions",
            help="functions frozen by the recompile-storm policy"
        ).set(float(n))
    except Exception:
        pass
    _flight("autopilot_freeze", function=function,
            policy=policy.name if policy else None,
            compiles=(finding or {}).get("compiles"))
    try:
        from horovod_tpu.common.logging import get_logger
        get_logger().error(
            "autopilot: function %r is in a recompile storm (%s "
            "compiles) — its input shapes/dtypes are drifting every "
            "step; pin them (pad the ragged batch, hash-check traced "
            "python scalars).  See docs/TROUBLESHOOTING.md.",
            function, (finding or {}).get("compiles", "?"))
    except Exception:
        pass


def frozen_functions() -> Set[str]:
    with _lock:
        return set(_frozen)


def register_rollback_hook(fn: Callable[[], None]) -> None:
    """Training loops that own restorable durable state register a
    zero-arg callable here (typically ``lambda: state.restore()`` over
    an elastic ``ObjectState``, or a ``restore_latest`` into the live
    pytree); the ``rollback_restore`` remediation runs every hook in
    the background when persistent ``grad_nonfinite`` findings fire."""
    with _lock:
        _rollback_hooks.append(fn)


def rollback(policy: Optional[Policy] = None,
             finding: Optional[dict] = None) -> int:
    """Persistent non-finite gradients: the optimizer state advancing
    under a poisoned data plane must not be the state that commits
    forward — restore the last durable checkpoint through the
    registered hooks.  Returns how many hooks ran.  With no hooks
    registered the decision is still a first-class audit artifact; the
    alert names what SHOULD have been restored."""
    with _lock:
        hooks = list(_rollback_hooks)
    ran = 0
    for fn in hooks:
        try:
            fn()
            ran += 1
        except Exception:
            try:
                from horovod_tpu.common.logging import get_logger
                get_logger().warning(
                    "autopilot: rollback hook %r failed", fn,
                    exc_info=True)
            except Exception:
                pass
    _flight("autopilot_rollback", policy=policy.name if policy else None,
            hooks=len(hooks), ran=ran,
            step=(finding or {}).get("step"),
            consecutive=(finding or {}).get("consecutive"))
    try:
        from horovod_tpu.common.logging import get_logger
        if hooks:
            get_logger().error(
                "autopilot: persistent non-finite gradients (%s "
                "consecutive skipped steps) — restored the last durable "
                "checkpoint via %d/%d rollback hook(s)",
                (finding or {}).get("consecutive", "?"), ran, len(hooks))
        else:
            get_logger().error(
                "autopilot: persistent non-finite gradients (%s "
                "consecutive skipped steps) and NO rollback hook is "
                "registered — restore the last committed checkpoint "
                "manually (docs/TROUBLESHOOTING.md \"My loss went "
                "NaN\")", (finding or {}).get("consecutive", "?"))
    except Exception:
        pass
    return ran


def register_scale_out_hook(fn: Callable[[], None]) -> None:
    """A serving fleet registers a zero-arg callable raising its
    replica target (``ReplicaFleet.register_autopilot_hook``); the
    ``scale_out`` remediation runs every hook when a sustained
    ``slo_breach`` finding fires (docs/SERVING.md)."""
    with _lock:
        _scale_out_hooks.append(fn)


def scale_out(policy: Optional[Policy] = None,
              finding: Optional[dict] = None) -> int:
    """Sustained serving SLO breach: capacity, not tuning, is the
    remediation the fleet owns — run the registered scale-out hooks.
    Returns how many ran; with none registered the decision is still a
    first-class audit artifact (the alert says what SHOULD have grown).
    """
    with _lock:
        hooks = list(_scale_out_hooks)
    ran = 0
    for fn in hooks:
        try:
            fn()
            ran += 1
        except Exception:
            try:
                from horovod_tpu.common.logging import get_logger
                get_logger().warning(
                    "autopilot: scale-out hook %r failed", fn,
                    exc_info=True)
            except Exception:
                pass
    _flight("autopilot_scale_out",
            policy=policy.name if policy else None, hooks=len(hooks),
            ran=ran, p99_s=(finding or {}).get("p99_s"),
            slo_s=(finding or {}).get("slo_s"))
    try:
        from horovod_tpu.common.logging import get_logger
        if hooks:
            get_logger().error(
                "autopilot: serving p99 %.4fs over SLO %.4fs — scaled "
                "the replica fleet out via %d/%d hook(s)",
                (finding or {}).get("p99_s", float("nan")),
                (finding or {}).get("slo_s", float("nan")),
                ran, len(hooks))
        else:
            get_logger().error(
                "autopilot: serving SLO breach (p99 %s over %s) and NO "
                "scale-out hook is registered — grow the replica fleet "
                "manually (docs/SERVING.md runbook)",
                (finding or {}).get("p99_s"),
                (finding or {}).get("slo_s"))
    except Exception:
        pass
    return ran


def register_promote_rollout_hook(fn: Callable[[dict], None]) -> None:
    """A rollout controller registers a one-arg callable (receiving the
    ``rollout_verdict`` finding) that advances its canary stage; the
    ``promote_rollout`` remediation runs every hook when a "promote"
    verdict fires (docs/SERVING.md "Canary rollout")."""
    with _lock:
        _promote_rollout_hooks.append(fn)


def register_rollback_rollout_hook(fn: Callable[[dict], None]) -> None:
    """A rollout controller registers a one-arg callable (receiving the
    ``rollout_verdict`` finding) that repins every canary replica to
    the incumbent version; the ``rollback_rollout`` remediation runs
    every hook when a "rollback" verdict fires."""
    with _lock:
        _rollback_rollout_hooks.append(fn)


def _run_rollout_hooks(which: str, hooks: List[Callable[[dict], None]],
                       policy: Optional[Policy], finding: Optional[dict],
                       decision: Optional[dict]) -> int:
    """Shared promote/rollback machinery: run the hooks INSIDE the
    decision's trace (finding → decision → action → repin flips share
    one id — the whole governed transition is one causal tree), record
    the flight event, and alert loudly either way."""
    finding = finding or {}
    from horovod_tpu import tracing
    actx = tracing.child(
        tracing.decode((decision or {}).get(tracing.TRACEPARENT)),
        "autopilot")
    ran = 0
    t0 = time.time()
    with tracing.activate(actx):
        for fn in hooks:
            try:
                fn(finding)
                ran += 1
            except Exception:
                try:
                    from horovod_tpu.common.logging import get_logger
                    get_logger().warning(
                        "autopilot: %s hook %r failed", which, fn,
                        exc_info=True)
                except Exception:
                    pass
    tracing.record_span("autopilot", which, actx, start=t0,
                        dur_s=time.time() - t0,
                        rollout=finding.get("rollout_id"),
                        verdict=finding.get("verdict"))
    _flight(f"autopilot_{which}",
            policy=policy.name if policy else None, hooks=len(hooks),
            ran=ran, verdict=finding.get("verdict"),
            rollout_id=finding.get("rollout_id"),
            candidate=finding.get("candidate"),
            incumbent=finding.get("incumbent"))
    return ran


def promote_rollout(policy: Optional[Policy] = None,
                    finding: Optional[dict] = None,
                    decision: Optional[dict] = None) -> int:
    """A "promote" rollout verdict: the candidate version beat its SLO
    comparison against the incumbent — advance the canary stage via
    the registered hooks.  Returns how many ran; with none registered
    the decision is still a first-class audit artifact."""
    with _lock:
        hooks = list(_promote_rollout_hooks)
    ran = _run_rollout_hooks("promote_rollout", hooks, policy, finding,
                             decision)
    try:
        from horovod_tpu.common.logging import get_logger
        f = finding or {}
        if hooks:
            get_logger().error(
                "autopilot: rollout %s — candidate v%s healthy vs "
                "incumbent v%s; advanced the canary stage via %d/%d "
                "hook(s)", f.get("rollout_id", "?"),
                f.get("candidate", "?"), f.get("incumbent", "?"),
                ran, len(hooks))
        else:
            get_logger().error(
                "autopilot: rollout %s verdict 'promote' and NO "
                "promote hook is registered — advance the rollout "
                "manually (docs/SERVING.md \"Canary rollout\")",
                f.get("rollout_id", "?"))
    except Exception:
        pass
    return ran


def rollback_rollout(policy: Optional[Policy] = None,
                     finding: Optional[dict] = None,
                     decision: Optional[dict] = None) -> int:
    """A "rollback" rollout verdict: the candidate version degraded
    latency/errors or diverged on the golden set — repin every canary
    replica to the incumbent through the registered hooks.  The repin
    is the same atomic between-batch flip as a hot swap, so in-flight
    requests finish on whichever version computed them and ZERO
    requests fail.  Returns how many hooks ran."""
    with _lock:
        hooks = list(_rollback_rollout_hooks)
    ran = _run_rollout_hooks("rollback_rollout", hooks, policy, finding,
                             decision)
    try:
        from horovod_tpu.common.logging import get_logger
        f = finding or {}
        if hooks:
            get_logger().error(
                "autopilot: rollout %s — candidate v%s FAILED its "
                "canary vs incumbent v%s (%s); repinned every canary "
                "replica to the incumbent via %d/%d hook(s)",
                f.get("rollout_id", "?"), f.get("candidate", "?"),
                f.get("incumbent", "?"), f.get("reason", "verdict"),
                ran, len(hooks))
        else:
            get_logger().error(
                "autopilot: rollout %s verdict 'rollback' and NO "
                "rollback hook is registered — repin the canary "
                "replicas to the incumbent manually (docs/SERVING.md "
                "\"Canary rollout\" runbook)", f.get("rollout_id", "?"))
    except Exception:
        pass
    return ran


def register_retune_hook(fn: Callable[[], None]) -> None:
    """Training loops that hold a live autotuned step register a zero-
    arg callable here; the ``retune`` remediation runs every hook (in
    the background) after invalidating the plan cache."""
    with _lock:
        _retune_hooks.append(fn)


def retune(policy: Optional[Policy] = None,
           finding: Optional[dict] = None) -> int:
    """Topology/world change: drop every persisted autotune plan (the
    tuned plans encode the OLD world's measured tradeoffs) and kick the
    registered re-tune hooks.  Returns how many cache entries were
    invalidated."""
    removed = 0
    try:
        from horovod_tpu.train.autotune import invalidate_plan_cache
        removed = invalidate_plan_cache()
    except Exception:
        try:
            from horovod_tpu.common.logging import get_logger
            get_logger().warning("autopilot: plan-cache invalidation "
                                 "failed", exc_info=True)
        except Exception:
            pass
    with _lock:
        hooks = list(_retune_hooks)
    for fn in hooks:
        try:
            fn()
        except Exception:
            try:
                from horovod_tpu.common.logging import get_logger
                get_logger().warning("autopilot: retune hook %r failed",
                                     fn, exc_info=True)
            except Exception:
                pass
    _flight("autopilot_retune", policy=policy.name if policy else None,
            invalidated=removed, hooks=len(hooks),
            old_size=(finding or {}).get("old_size"),
            new_size=(finding or {}).get("new_size"))
    try:
        from horovod_tpu.common.logging import get_logger
        get_logger().warning(
            "autopilot: topology change — invalidated %d cached "
            "autotune plan(s), ran %d retune hook(s)", removed,
            len(hooks))
    except Exception:
        pass
    return removed


def reset() -> None:
    """Tests: forget frozen functions, hooks, and the action sequence."""
    global _seq
    with _lock:
        _frozen.clear()
        _retune_hooks.clear()
        _rollback_hooks.clear()
        _scale_out_hooks.clear()
        _promote_rollout_hooks.clear()
        _rollback_rollout_hooks.clear()
        _seq = 0
