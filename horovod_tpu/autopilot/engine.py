"""The policy engine: anomaly findings in, audited decisions out.

One :class:`PolicyEngine` per process, subscribed to every finding the
anomaly engine flags (:mod:`horovod_tpu.metrics.anomaly` calls
:func:`horovod_tpu.autopilot.on_finding` from ``_flag`` — the native
step/fleet detectors and external ``report_finding()`` detectors take
the identical path).  For each finding it evaluates the matching
policies' gates IN ORDER — hysteresis, cooldown, action budget, then
the action-specific SLO gate — and emits exactly one decision per
(policy, finding):

* ``fired``      — all gates passed and ``HVD_TPU_AUTOPILOT=act``: the
  remediation dispatches (:mod:`horovod_tpu.autopilot.actions`);
* ``dry_run``    — all gates passed under ``observe``: the decision is
  recorded IDENTICALLY (cooldown and budget bookkeeping advance the
  same way), nothing acts — run the same chaos plan under both modes
  and the audit trails must match except for the outcome field;
* ``suppressed`` — a gate refused, with the reason
  (``hysteresis`` / ``cooldown`` / ``budget`` / ``slo``) and the gate's
  inputs recorded.

Every decision lands four ways (docs/OBSERVABILITY.md "Autopilot"):
``hvd_autopilot_decisions_total{policy=,outcome=}`` (and
``hvd_autopilot_actions_total{action=}`` for fired ones) on
``/metrics``, an ``autopilot_decision`` flight event carrying the gate
inputs, a bounded in-memory ring the autopsy summary embeds under
``actions``, and — when ``HVD_TPU_OBS_DIR`` is set — an append-only
``actions_rank<r>.jsonl`` log rendered by
``python -m horovod_tpu.metrics history --actions``.

The drain_and_replace SLO gate is the re-mesh timeline history
(docs/OBSERVABILITY.md "Re-mesh timeline"): the measured p50 recovery
cost of past episodes, against the straggler's projected loss over the
policy's horizon — the cure must beat the disease, with receipts.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Deque, Dict, List, Optional

from horovod_tpu.autopilot.policy import (Policy, load_policies_from_env,
                                          mode as policy_mode)

MAX_DECISIONS = 256

_MODE_VALUE = {"off": 0.0, "observe": 1.0, "act": 2.0}


def _median(values: List[float]) -> Optional[float]:
    if not values:
        return None
    s = sorted(values)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def remesh_p50_s() -> Optional[float]:
    """Measured p50 of completed re-mesh episodes, from the time-series
    history (the in-memory ring plus, when ``HVD_TPU_OBS_DIR`` is set,
    the persisted JSONL — a restarted rank 0 keeps its evidence).
    None when no episode was ever measured: with no evidence a re-mesh
    is expensive, the gate has nothing to refuse on."""
    def _key(p, v):
        return (p.get("ts"), round(float(v), 6))

    totals: List[float] = []
    try:
        from horovod_tpu.metrics import timeseries
        d = timeseries.obs_dir()
        disk = timeseries.read_series(d) if d else []
        disk_keys = set()
        for p in disk:
            v = p.get("remesh_total_s")
            if isinstance(v, (int, float)) and p.get("complete", True):
                totals.append(float(v))
                disk_keys.add(_key(p, v))
        for p in timeseries.recorder().ring.points():
            v = p.get("remesh_total_s")
            if isinstance(v, (int, float)) and p.get("complete", True):
                # an episode still in the ring is usually ALSO on disk
                # (the recorder writes both); counting it twice would
                # weight the p50 toward recent episodes and skew the
                # SLO gate — only ring points the disk does not already
                # hold (persistence off, write failed, rotated away)
                # contribute
                if _key(p, v) in disk_keys:
                    continue
                totals.append(float(v))
    except Exception:
        return None
    return _median(totals)


class _PolicyState:
    """Per-(policy, key) gate bookkeeping."""

    def __init__(self) -> None:
        self.streak = 0
        self.cooldown_until = 0.0
        self.fired_at: Deque[float] = collections.deque()


class PolicyEngine:
    """Evaluate findings against the policy set; record every decision.

    ``mode``/``policies``/``registry`` are injectable for tests; the
    process-wide instance reads them from env
    (:func:`horovod_tpu.autopilot.default_engine`).
    """

    def __init__(self, policies: Optional[List[Policy]] = None,
                 registry=None, mode: Optional[str] = None,
                 rank: Optional[int] = None) -> None:
        self.policies = load_policies_from_env() \
            if policies is None else list(policies)
        self.mode = policy_mode() if mode is None else mode
        self._by_finding: Dict[str, List[Policy]] = {}
        for p in self.policies:
            self._by_finding.setdefault(p.finding, []).append(p)
        self._reg = registry
        self._lock = threading.Lock()
        self._state: Dict[tuple, _PolicyState] = {}
        self.decisions: Deque[dict] = collections.deque(
            maxlen=MAX_DECISIONS)
        if rank is None:
            from horovod_tpu.diagnostics.flight_recorder import (
                _best_effort_rank)
            rank = _best_effort_rank()
        self.rank = rank
        self._writer = None
        self._writer_dir = None
        # own lock: _log_jsonl runs from _decide, which suppressed-path
        # callers may reach with gate state of their own in play — the
        # writer must never share the gate lock
        self._writer_lock = threading.Lock()
        try:
            self._registry().gauge(
                "hvd_autopilot_mode",
                help="autopilot mode (0=off, 1=observe, 2=act)").set(
                _MODE_VALUE.get(self.mode, 1.0))
        except Exception:
            pass

    def _registry(self):
        if self._reg is None:
            from horovod_tpu.metrics.registry import default_registry
            self._reg = default_registry()
        return self._reg

    def refresh_identity(self) -> None:
        """Re-read this process's rank — an elastic re-mesh can
        renumber us, and the engine deliberately SURVIVES re-init (its
        cooldown/budget state must not reset with every world change),
        so the identity stamped into decisions and the JSONL filename
        has to follow the live env instead (the preemption watcher
        makes the same call)."""
        from horovod_tpu.diagnostics.flight_recorder import (
            _best_effort_rank)
        rank = _best_effort_rank()
        with self._writer_lock:
            if rank != self.rank:
                self.rank = rank
                self._writer = None  # reopen as actions_rank<new>
                self._writer_dir = None

    # -- the subscription seam ----------------------------------------------
    def on_finding(self, finding: dict) -> List[dict]:
        """Evaluate one finding; returns the decisions recorded (one per
        matching policy, [] when no policy subscribes to the kind).
        Called with the anomaly engine's lock held — everything here is
        in-process bookkeeping; a fired action's KV traffic happens on a
        background thread (:mod:`horovod_tpu.autopilot.actions`)."""
        kind = finding.get("kind")
        out = []
        for policy in self._by_finding.get(kind, ()):
            try:
                out.append(self._evaluate(policy, finding))
            except Exception:
                # a broken gate must never break detection
                try:
                    from horovod_tpu.common.logging import get_logger
                    get_logger().warning(
                        "autopilot: policy %r failed on finding %r",
                        policy.name, kind, exc_info=True)
                except Exception:
                    pass
        return out

    # -- gates ---------------------------------------------------------------
    def _evaluate(self, policy: Policy, finding: dict) -> dict:
        key = None
        if policy.key_field is not None:
            key = finding.get(policy.key_field)
        now = time.monotonic()
        gate: Dict[str, Any] = {}
        # the gate verdict is computed under the lock; the decision is
        # RECORDED outside it (_decide fans out to the JSONL writer,
        # registry, and flight ring — none of which may nest under this
        # non-reentrant lock)
        reason: Optional[str] = None
        with self._lock:
            st = self._state.setdefault((policy.name, key),
                                        _PolicyState())
            st.streak += 1
            gate["streak"] = st.streak
            if st.streak < policy.hysteresis:
                gate["hysteresis"] = policy.hysteresis
                reason = "hysteresis"
            elif now < st.cooldown_until:
                gate["cooldown_remaining_s"] = round(
                    st.cooldown_until - now, 1)
                reason = "cooldown"
            else:
                while st.fired_at and \
                        now - st.fired_at[0] > policy.window_s:
                    st.fired_at.popleft()
                gate["actions_in_window"] = len(st.fired_at)
                if len(st.fired_at) >= policy.max_actions:
                    gate["max_actions"] = policy.max_actions
                    reason = "budget"
        if reason is not None:
            return self._decide(policy, finding, key, "suppressed",
                                reason, gate)
        ok, slo_gate = self._slo_gate(policy, finding)
        gate.update(slo_gate)
        if not ok:
            return self._decide(policy, finding, key, "suppressed",
                                "slo", gate)
        # all gates passed: the decision is made — observe records it
        # without acting, and the bookkeeping advances IDENTICALLY so
        # both modes produce the same decision stream
        with self._lock:
            st = self._state[(policy.name, key)]
            st.streak = 0
            st.cooldown_until = now + policy.cooldown_s
            st.fired_at.append(now)
        outcome = "fired" if self.mode == "act" else "dry_run"
        decision = self._decide(policy, finding, key, outcome, None, gate)
        if outcome == "fired":
            try:
                self._registry().counter(
                    "hvd_autopilot_actions_total",
                    help="autopilot remediations dispatched, per action",
                    labels={"action": policy.action}).inc()
            except Exception:
                pass
            from horovod_tpu.autopilot import actions
            actions.dispatch(policy, finding, decision)
        return decision

    def _slo_gate(self, policy: Policy, finding: dict) -> tuple:
        """(passes, gate-inputs) for the policy's action.  Every input
        consulted lands in the decision — a suppressed remediation must
        say what number stopped it."""
        if policy.action == "drain_and_replace":
            gate: Dict[str, Any] = {"horizon_steps": policy.horizon_steps}
            p50 = remesh_p50_s()
            gate["remesh_p50_s"] = round(p50, 4) if p50 is not None \
                else None
            excess = None
            win = finding.get("win_step_time")
            mean = finding.get("fleet_mean")
            if isinstance(win, (int, float)) and \
                    isinstance(mean, (int, float)):
                excess = max(0.0, float(win) - float(mean))
                gate["straggler_excess_s"] = round(excess, 4)
                gate["projected_loss_s"] = round(
                    excess * policy.horizon_steps, 4)
            if policy.max_remesh_p50_s > 0 and p50 is not None \
                    and p50 > policy.max_remesh_p50_s:
                gate["max_remesh_p50_s"] = policy.max_remesh_p50_s
                return False, gate
            if p50 is not None and excess is not None \
                    and excess * policy.horizon_steps <= p50:
                # the cure measurably costs more than the disease
                return False, gate
            return True, gate
        if policy.action == "commit_restart":
            gate = {"max_margin_frac": policy.max_margin_frac}
            margin = limit = None
            try:
                reg = self._registry()
                m = reg.get("hvd_hbm_oom_margin_bytes")
                li = reg.get("hvd_hbm_limit_bytes")
                margin = m.value if m is not None else None
                limit = li.value if li is not None else None
            except Exception:
                pass
            gate["oom_margin_bytes"] = margin
            gate["limit_bytes"] = limit
            if not limit:
                # growth alone is not "past the OOM margin": without a
                # margin measurement the planned restart stays parked
                return False, gate
            frac = max(0.0, float(margin or 0.0)) / float(limit)
            gate["margin_frac"] = round(frac, 4)
            return frac < policy.max_margin_frac, gate
        if policy.action in ("promote_rollout", "rollback_rollout"):
            # both rollout policies subscribe to the SAME
            # rollout_verdict finding; the verdict field routes it to
            # exactly one of them — the other's decision is suppressed
            # here with the mismatched verdict recorded as the reason
            want = "promote" if policy.action == "promote_rollout" \
                else "rollback"
            verdict = finding.get("verdict")
            return verdict == want, {"verdict": verdict, "want": want}
        return True, {}

    # -- the audit trail -----------------------------------------------------
    def _decide(self, policy: Policy, finding: dict, key,
                outcome: str, reason: Optional[str],
                gate: Dict[str, Any]) -> dict:
        decision = {
            "ts": round(time.time(), 3),
            "policy": policy.name,
            "action": policy.action,
            "finding": finding.get("kind"),
            "outcome": outcome,
            "mode": self.mode,
            "rank": self.rank,
            "gate": gate,
        }
        try:
            # continue the finding's trace: the decision is a child
            # span, and fired remediations child from the decision (the
            # action/ doc carries decision["traceparent"] to the
            # driver) — docs/OBSERVABILITY.md "Causal tracing"
            from horovod_tpu import tracing
            dctx = tracing.child(
                tracing.decode(finding.get(tracing.TRACEPARENT)),
                "autopilot")
            if dctx is not None:
                decision.update(dctx.fields())
                decision[tracing.TRACEPARENT] = dctx.traceparent
        except Exception:
            pass
        if reason is not None:
            decision["reason"] = reason
        if key is not None:
            decision["key"] = key
        if isinstance(finding.get("rank"), int):
            decision["target_rank"] = finding["rank"]
        if isinstance(finding.get("step"), int):
            decision["step"] = finding["step"]
        self.decisions.append(decision)
        try:
            self._registry().counter(
                "hvd_autopilot_decisions_total",
                help="autopilot policy decisions, per policy and outcome",
                labels={"policy": policy.name,
                        "outcome": outcome}).inc()
        except Exception:
            pass
        try:
            from horovod_tpu.diagnostics.flight_recorder import (
                record_event)
            record_event("autopilot_decision",
                         **{k: v for k, v in decision.items()
                            if k not in ("ts", "traceparent")})
        except Exception:
            pass
        self._log_jsonl(decision)
        try:
            from horovod_tpu.common.logging import get_logger
            log = get_logger()
            if outcome == "fired":
                log.warning("autopilot: FIRING %s (policy %s) on %s %s",
                            policy.action, policy.name,
                            decision["finding"], gate)
            elif outcome == "dry_run":
                log.warning("autopilot[observe]: would fire %s (policy "
                            "%s) on %s %s", policy.action, policy.name,
                            decision["finding"], gate)
            else:
                log.info("autopilot: suppressed %s (policy %s, %s) %s",
                         policy.action, policy.name, reason, gate)
        except Exception:
            pass
        return decision

    def _log_jsonl(self, decision: dict) -> None:
        """Bounded action log (``HVD_TPU_OBS_DIR`` unset = ring only),
        same writer/size-rotation machinery as the step series —
        ``actions_rank<r>.jsonl`` rotates at
        ``HVD_TPU_ACTIONS_MAX_BYTES`` (default: the OBS store's bound)
        with one previous generation kept; ``history --actions`` reads
        across the boundary."""
        try:
            from horovod_tpu.common.config import env_int
            from horovod_tpu.metrics import timeseries
            d = timeseries.obs_dir()
            if not d:
                return
            with self._writer_lock:
                if self._writer is None or self._writer_dir != d:
                    self._writer = timeseries.SeriesWriter(
                        d, rank=self.rank, basename="actions",
                        max_bytes=env_int("ACTIONS_MAX_BYTES", 0)
                        or None)
                    self._writer_dir = d
                writer = self._writer
            writer.write(decision)
        except Exception:
            pass

    def recent_decisions(self, last_n: int = MAX_DECISIONS) -> List[dict]:
        return list(self.decisions)[-last_n:]
