"""Elastic state for TF/Keras training.

Reference: ``horovod/tensorflow/elastic.py`` (``TensorFlowKerasState`` /
``TensorFlowState``: snapshot + broadcast-based sync of variables). Same
pattern as the torch adapter's ``TorchState``: model weights (and keras
optimizer variables) are snapshotted WITH the scalar attributes as one
commit/restore/sync unit, persisted across generation restarts when the
elastic driver manages the job.
"""

from __future__ import annotations

import numpy as np

from horovod_tpu.elastic import ObjectState, run  # noqa: F401 (re-export)


def _opt_vars(optimizer):
    if optimizer is None:
        return None
    v = getattr(optimizer, "variables", None)
    if v is None:
        return None
    return v() if callable(v) else v


class TensorFlowKerasState(ObjectState):
    """Reference: ``TensorFlowKerasState`` (``tensorflow/elastic.py``)."""

    def __init__(self, model, optimizer=None,
                 name: str = "tf_keras_state", **kwargs) -> None:
        self._model = model
        self._optimizer = optimizer
        super().__init__(name=name, keras_snaps=self._capture(), **kwargs)
        self._apply(self.keras_snaps)

    def _capture(self) -> dict:
        opt = _opt_vars(self._optimizer)
        return dict(
            weights=[np.asarray(w) for w in self._model.get_weights()],
            opt_weights=[np.asarray(v) for v in opt] if opt else None)

    def _apply(self, snaps: dict) -> None:
        if snaps.get("weights"):
            self._model.set_weights(snaps["weights"])
        opt = _opt_vars(self._optimizer)
        if snaps.get("opt_weights") and opt:
            for var, val in zip(opt, snaps["opt_weights"]):
                if tuple(var.shape) == np.asarray(val).shape:
                    var.assign(val)

    def save(self) -> None:
        self.keras_snaps = self._capture()
        super().save()

    def restore(self) -> None:
        super().restore()
        self._apply(self.keras_snaps)

    def sync(self) -> None:
        # rank 0's live weights are the source of truth; ObjectState.sync
        # broadcasts the snapshot dict together with the scalars
        self.keras_snaps = self._capture()
        super().sync()
        self._apply(self.keras_snaps)


class TensorFlowState(ObjectState):
    """Raw-variable elastic state (reference: ``TensorFlowState`` — the
    non-Keras variant syncing an explicit variable list rather than a
    model object)."""

    def __init__(self, variables, name: str = "tf_state",
                 **kwargs) -> None:
        self._vars = list(variables)
        super().__init__(name=name, var_snaps=self._capture(), **kwargs)
        self._apply(self.var_snaps)

    def _capture(self) -> list:
        return [np.asarray(v) for v in self._vars]

    def _apply(self, snaps: list) -> None:
        if not snaps:
            return
        for var, val in zip(self._vars, snaps):
            if tuple(var.shape) == np.asarray(val).shape:
                var.assign(val)

    def save(self) -> None:
        self.var_snaps = self._capture()
        super().save()

    def restore(self) -> None:
        super().restore()
        self._apply(self.var_snaps)

    def sync(self) -> None:
        self.var_snaps = self._capture()
        super().sync()
        self._apply(self.var_snaps)


def _keras_callbacks_base():
    import tensorflow as tf
    return tf.keras.callbacks.Callback


def CommitStateCallback(state, batches_per_commit: int = 1):
    """Commit the elastic state every ``batches_per_commit`` batches and
    at every epoch end (reference: ``CommitStateCallbackImpl``,
    ``_keras/elastic.py:17-40``).

    List this LAST in ``callbacks`` (reference usage order) so each
    commit captures the Update*StateCallback counters for the same
    batch/epoch — keras runs callbacks in list order. Factory function
    returning a ``tf.keras.callbacks.Callback`` instance."""

    class _Impl(_keras_callbacks_base()):
        def __init__(self):
            super().__init__()
            self._remaining = batches_per_commit

        def on_train_begin(self, logs=None):
            # reset on every sync event for cross-rank consistency
            self._remaining = batches_per_commit

        def on_batch_end(self, batch, logs=None):
            self._remaining -= 1
            if self._remaining == 0:
                state.commit()
                self._remaining = batches_per_commit

        def on_epoch_end(self, epoch, logs=None):
            state.commit()

    return _Impl()


def UpdateBatchStateCallback(state):
    """Track the COMPLETED-batch count in ``state.batch`` through fit
    (reference: ``UpdateBatchStateCallbackImpl``,
    ``_keras/elastic.py:42-63``).

    Like the reference, the first ``on_epoch_begin`` after a restore
    with ``state.batch > 0`` reduces ``self.params["steps"]`` by the
    already-committed batch count (restored at epoch end so later
    epochs run full length). Only LEGACY training loops (tf.keras
    before the 2.2 DataHandler rewrite) honor that mutation; every
    modern tf.keras / Keras 3 loop takes its step count from the data
    handler and merely shows the shrunk count in the progress bar. On
    modern keras the CALLER must therefore compensate — pass
    ``steps_per_epoch - state.batch`` (or slice the dataset) to the
    post-restore ``fit`` — else the committed epoch's earlier batches
    replay.

    ``state.batch`` counts completed batches WITHIN THE CURRENT RUN of
    the epoch (matching the reference). After a mid-epoch resume the
    count therefore lags the true position in the original epoch by
    the resumed offset, so a commit taken inside a resumed epoch can
    only cause a later restore to RE-train a few batches — never to
    skip training. Callers wanting exact positions after a resume
    should commit at epoch boundaries (``batches_per_commit`` large,
    or rely on the epoch-end commit). Factory function returning a
    callback."""

    class _Impl(_keras_callbacks_base()):
        def __init__(self):
            super().__init__()
            self._saved_steps = None

        def on_epoch_begin(self, epoch, logs=None):
            if state.batch > 0:
                steps = (self.params or {}).get("steps")
                if steps:
                    self._saved_steps = steps
                    self.params["steps"] = max(steps - state.batch, 0)

        def on_batch_end(self, batch, logs=None):
            state.batch = batch + 1  # completed count, not last index

        def on_epoch_end(self, epoch, logs=None):
            if self._saved_steps is not None:
                # later epochs start from 0 and must run full length
                self.params["steps"] = self._saved_steps
                self._saved_steps = None
            state.batch = 0

    return _Impl()


def UpdateEpochStateCallback(state):
    """Track the GLOBAL epoch number in ``state.epoch`` across resets —
    keras restarts epoch numbering at 0 on every retry (reference:
    ``UpdateEpochStateCallbackImpl``, ``_keras/elastic.py:66-89``).
    Factory function returning a callback."""

    class _Impl(_keras_callbacks_base()):
        def __init__(self):
            super().__init__()
            self._initial_epoch = state.epoch

        def on_train_begin(self, logs=None):
            self._initial_epoch = state.epoch

        def on_epoch_end(self, epoch, logs=None):
            # +1: a reset after state.batch returns to 0 must not repeat
            # the finished epoch
            state.epoch = self._initial_epoch + epoch + 1

    return _Impl()
