"""``horovod_tpu.tensorflow`` — drop-in surface for reference TF users.

Reference: ``horovod/tensorflow/__init__.py`` (``hvd.allreduce`` :55-162,
``broadcast_variables``/``broadcast_global_variables`` :284,
``DistributedOptimizer`` :627, ``DistributedGradientTape`` :777) and
``horovod/tensorflow/mpi_ops.py``. TF runs host-side (CPU) here — the TPU
compute path is JAX — so this adapter carries a TF input/metrics pipeline's
distribution layer while models migrate.
"""

from __future__ import annotations

import zlib
from typing import Optional

import numpy as np

# identity / lifecycle re-exports (reference: tensorflow/mpi_ops.py)
from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, is_homogeneous, mpi_threads_supported,
    mpi_built, gloo_built, nccl_built, ccl_built, cuda_built, rocm_built,
    ddl_built, sycl_built, mpi_enabled, gloo_enabled,
    start_timeline, stop_timeline)
from horovod_tpu.common.process_sets import (  # noqa: F401
    ProcessSet, add_process_set, remove_process_set, global_process_set)
from horovod_tpu.ops.reduce_op import (  # noqa: F401
    Adasum, Average, Max, Min, Product, ReduceOp, Sum)
from horovod_tpu.ops import collectives as _C
from horovod_tpu.train.compression import Compression  # noqa: F401


def _tf():
    import tensorflow as tf
    return tf


def _to_np(tensor) -> np.ndarray:
    if hasattr(tensor, "numpy"):
        return tensor.numpy()
    return np.asarray(tensor)


def _from_np(arr, like):
    tf = _tf()
    return tf.constant(np.asarray(arr), dtype=like.dtype)


def _sparse_reduce(tf, g, op, name, process_set):
    """Allgather-based reduce of ONE IndexedSlices gradient, keeping it
    sparse end-to-end (reference: ``sparse_allreduce_async``,
    ``torch/mpi_ops.py:515-535`` — same contract as the torch adapter's
    sparse path): every rank's indices and values concatenate; duplicate
    coordinates sum in the optimizer's sparse apply, so dividing values
    by the world size yields the elementwise average."""

    def do(values, indices):
        if size() <= 1:
            return [np.asarray(values), np.asarray(indices)]
        vh = _C.allgather_async(np.asarray(values), name=f"{name}.v",
                                process_set=process_set)
        ih = _C.allgather_async(np.asarray(indices), name=f"{name}.i",
                                process_set=process_set)
        v, i = vh.wait(), ih.wait()
        if op == Average:
            v = v / process_set.size()
        return [np.asarray(v), np.asarray(i)]

    if tf.executing_eagerly():
        v, i = do(g.values, g.indices)
        return tf.IndexedSlices(
            tf.constant(v, dtype=g.values.dtype),
            tf.constant(i, dtype=g.indices.dtype), g.dense_shape)
    v, i = tf.py_function(do, [g.values, g.indices],
                          [g.values.dtype, g.indices.dtype])
    v.set_shape(tf.TensorShape([None]).concatenate(g.values.shape[1:]))
    i.set_shape([None])
    return tf.IndexedSlices(v, i, g.dense_shape)


def _host_grouped_allreduce(grads, compression, op, prefix, process_set,
                            var_names=None):
    """Shared eager/graph gradient-allreduce body for the tape and the
    optimizer: compress → TCP-core grouped allreduce → decompress over the
    non-None dense entries; IndexedSlices entries stay sparse via the
    allgather path (_sparse_reduce). Inside a tf.function the work rides
    py_functions so the world size and the collectives resolve at graph
    EXECUTION time (same contract as size_op below — an elastic resize
    after tracing must take effect without retracing).

    Collective names derive from the variable names (when the caller
    knows them — the reference names every allreduce after its variable)
    plus gradient positions/shapes/dtypes: stable across steps and across
    re-wrapped tape instances (so the ResponseCache keeps hitting), yet
    distinct for distinct models. In graph mode a trace-time
    graph-unique suffix additionally separates two structurally identical
    calls in ONE traced step (WGAN-GP-style double gradients over the
    same variables): their py_function ops are unordered, so name reuse
    could cross-match across ranks; trace order is deterministic under
    SPMD, so the suffix agrees on every rank. Eager calls run
    synchronously in program order and need no suffix."""
    present = [i for i, g in enumerate(grads) if g is not None]
    if not present:
        return grads
    tf = _tf()
    if tf.executing_eagerly() and size() <= 1:
        return grads
    struct = ",".join(
        f"{i}:{var_names[i] if var_names else ''}:"
        f"{grads[i].shape}:{grads[i].dtype.name}" for i in present)
    name = f"{prefix}.{zlib.crc32(struct.encode()):08x}"
    if not tf.executing_eagerly():
        # keep the FULL scoped path — the scope is part of what makes
        # unique_name unique ('gen/tfgrad' vs 'disc/tfgrad')
        uid = tf.compat.v1.get_default_graph().unique_name(
            prefix).replace("/", ".")
        name = f"{name}.{uid}"

    result = list(grads)
    sparse = [i for i in present
              if isinstance(grads[i], tf.IndexedSlices)]
    for i in sparse:
        result[i] = _sparse_reduce(tf, grads[i], op, f"{name}.s{i}",
                                   process_set)
    dense = [i for i in present if i not in sparse]
    if not dense:
        return result

    def do(*gs):
        if size() <= 1:
            return [np.asarray(g) for g in gs]
        comp, ctxs = [], []
        for g in gs:
            c, ctx = compression.compress(np.asarray(g))
            comp.append(np.asarray(c))
            ctxs.append(ctx)
        outs = _C.grouped_allreduce(comp, op=op, name=name,
                                    process_set=process_set)
        return [np.asarray(compression.decompress(
            np.asarray(o), ctx)) for o, ctx in zip(outs, ctxs)]

    if tf.executing_eagerly():
        outs = do(*[_to_np(grads[i]) for i in dense])
        for i, o in zip(dense, outs):
            result[i] = _from_np(o, grads[i])
        return result
    flat = tf.py_function(do, [grads[i] for i in dense],
                          [grads[i].dtype for i in dense])
    if not isinstance(flat, (list, tuple)):
        flat = [flat]
    for i, o in zip(dense, flat):
        o.set_shape(grads[i].shape)
        result[i] = o
    return result


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, op: Optional[ReduceOp] = None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set: ProcessSet = global_process_set):
    """Reference: ``hvd.allreduce`` (``tensorflow/__init__.py:55-162``)."""
    out = _C.allreduce(_to_np(tensor), average, name, op, prescale_factor,
                       postscale_factor, process_set)
    return _from_np(out, tensor)


def grouped_allreduce(tensors, average: Optional[bool] = None,
                      name: Optional[str] = None,
                      op: Optional[ReduceOp] = None,
                      process_set: ProcessSet = global_process_set):
    outs = _C.grouped_allreduce([_to_np(t) for t in tensors], average, name,
                                op, process_set=process_set)
    return [_from_np(o, t) for o, t in zip(outs, tensors)]


def allgather(tensor, name: Optional[str] = None,
              process_set: ProcessSet = global_process_set):
    return _from_np(_C.allgather(_to_np(tensor), name, process_set), tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              process_set: ProcessSet = global_process_set):
    return _from_np(_C.broadcast(_to_np(tensor), root_rank, name,
                                 process_set), tensor)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set: ProcessSet = global_process_set):
    """Received splits are returned ONLY when ``splits`` was supplied
    (reference return contract, ``tensorflow/mpi_ops.py`` alltoall)."""
    t, recv_splits = _C.alltoall(
        _to_np(tensor),
        None if splits is None else _to_np(splits), name, process_set)
    tf = _tf()
    gathered = _from_np(t, tensor)
    if splits is None:
        return gathered
    return gathered, tf.constant(np.asarray(recv_splits))


def join(device: int = -1) -> int:
    return _C.join(device)


def barrier(process_set: ProcessSet = global_process_set) -> None:
    _C.barrier(process_set)


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None):
    from horovod_tpu.train.optimizer import broadcast_object as _bo
    return _bo(obj, root_rank, name=name)


def allgather_object(obj, name: Optional[str] = None):
    """Reference: ``allgather_object`` (``torch/functions.py:233-266``)."""
    from horovod_tpu.train.optimizer import allgather_object as _ag
    return _ag(obj, name=name)


def broadcast_object_fn(root_rank: int = 0, session=None,
                        name: Optional[str] = None):
    """Reference: ``broadcast_object_fn`` (``tensorflow/functions.py:103``)
    — there a TF1 graph of placeholders bound to a session; eager TF2 needs
    no prebuilt graph, so this returns a closure over broadcast_object
    with the same call shape (``session`` accepted for signature parity)."""
    del session
    return lambda obj: broadcast_object(obj, root_rank, name=name)


# -- variable broadcast (reference: broadcast_variables /
# broadcast_global_variables, tensorflow/__init__.py:270-300) ---------------

def broadcast_variables(variables, root_rank: int = 0) -> None:
    """In-place broadcast of tf.Variables from root."""
    handles = [(v, _C.broadcast_async(_to_np(v), root_rank,
                                      name=f"bv.{i}"))
               for i, v in enumerate(variables)]
    for v, h in handles:
        v.assign(_from_np(h.wait(), v))


def broadcast_global_variables(root_rank: int = 0) -> None:
    """TF1-style global-variables broadcast (reference:
    ``broadcast_global_variables``); in TF2 prefer
    :func:`broadcast_variables` on ``model.variables``."""
    tf = _tf()
    if hasattr(tf.compat.v1, "global_variables"):
        broadcast_variables(tf.compat.v1.global_variables(), root_rank)


# -- graph-mode identity ops (reference: tensorflow/mpi_ops.py:361-440) -----
# Each resolves its value at graph EXECUTION time (py_function), so a
# tf.function traced in one environment reports the world it executes in —
# the reference's contract for size_op/rank_op under elastic resizes.

PROCESS_SET_ERROR_INIT = -1
PROCESS_SET_ERROR_UNKNOWN_SET = -2


def _exec_time_int(fn, name):
    tf = _tf()
    return tf.py_function(lambda: fn(), [], tf.int32, name=name)


def _identity_or_sentinel(fn):
    """-1 before hvd.init(), matching the reference C-API contract for
    horovod_size()/horovod_rank() (so probing graphs don't error)."""
    from horovod_tpu.common import basics

    def val():
        if not basics.is_initialized():
            return -1
        return fn()
    return val


def size_op(process_set_id: int = 0, name: Optional[str] = None):
    """Execution-time world (or process-set) size."""
    from horovod_tpu.common import basics, process_sets

    def val():
        if process_set_id:
            return process_sets.get_process_set_by_id(
                process_set_id).size()
        return basics.size()
    return _exec_time_int(_identity_or_sentinel(val),
                          name or "HorovodSize")


def local_size_op(name: Optional[str] = None):
    from horovod_tpu.common import basics
    return _exec_time_int(_identity_or_sentinel(basics.local_size),
                          name or "HorovodLocalSize")


def rank_op(name: Optional[str] = None):
    from horovod_tpu.common import basics
    return _exec_time_int(_identity_or_sentinel(basics.rank),
                          name or "HorovodRank")


def local_rank_op(name: Optional[str] = None):
    from horovod_tpu.common import basics
    return _exec_time_int(_identity_or_sentinel(basics.local_rank),
                          name or "HorovodLocalRank")


def process_set_included_op(process_set_id: int = 0,
                            name: Optional[str] = None):
    """1/0 whether this process is in the set; negative error codes match
    the reference (init / unknown-set)."""
    from horovod_tpu.common import basics, process_sets

    def val():
        if not basics.is_initialized():
            return PROCESS_SET_ERROR_INIT
        try:
            ps = process_sets.get_process_set_by_id(process_set_id)
        except (KeyError, ValueError):
            return PROCESS_SET_ERROR_UNKNOWN_SET
        return 1 if ps.included() else 0
    return _exec_time_int(val, name or "HorovodProcessSetIncluded")


# -- DistributedGradientTape (reference: tensorflow/__init__.py:777-851) ----

class _DistributedGradientTape:
    def __init__(self, tape, op: ReduceOp = Average,
                 compression=Compression.none,
                 process_set: ProcessSet = global_process_set) -> None:
        self._tape = tape
        self._op = op
        self._compression = compression
        self._process_set = process_set

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        # the reference tape accepts a lone Variable or any nest as
        # sources; flatten for the grouped allreduce and restore the
        # caller's structure afterwards
        tf = _tf()
        flat_src = tf.nest.flatten(sources)
        flat_grads = tf.nest.flatten(grads, expand_composites=False)
        names = [getattr(v, "name", "") for v in flat_src]
        out = self._allreduce_grads(flat_grads, names)
        return tf.nest.pack_sequence_as(grads, out,
                                        expand_composites=False)

    def _allreduce_grads(self, grads, var_names=None):
        return _host_grouped_allreduce(grads, self._compression, self._op,
                                       "tfgrad", self._process_set,
                                       var_names)


def DistributedGradientTape(gradtape, op: ReduceOp = Average,
                            compression=Compression.none,
                            process_set: ProcessSet = global_process_set):
    """Reference factory (``tensorflow/__init__.py:777``)."""
    return _DistributedGradientTape(gradtape, op, compression, process_set)


# -- DistributedOptimizer (reference: tensorflow/__init__.py:453-627) -------

class _DistributedOptimizer:
    """Wraps a keras optimizer: gradients are averaged across workers
    before ``apply_gradients`` (reference ``_DistributedOptimizer``)."""

    def __init__(self, optimizer, op: ReduceOp = Average,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1,
                 process_set: ProcessSet = global_process_set) -> None:
        self._opt = optimizer
        self._op = op
        self._compression = compression
        self._process_set = process_set
        self.backward_passes_per_step = backward_passes_per_step
        self._pass = 0
        self._acc: Optional[list] = None
        # graph-mode aggregation state (reference:
        # tensorflow/gradient_aggregation.py LocalGradientAggregationHelper)
        self._agg_vars: Optional[list] = None
        self._agg_counter = None

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def _sync(self, grads, tvars=None):
        names = [v.name for v in tvars] if tvars else None
        return _host_grouped_allreduce(grads, self._compression, self._op,
                                       "tfopt", self._process_set, names)

    def apply_gradients(self, grads_and_vars, *args, **kwargs):
        gv = list(grads_and_vars)
        grads = [g for g, _ in gv]
        tvars = [v for _, v in gv]
        # local accumulation for backward_passes_per_step (reference:
        # LocalGradientAggregationHelper, tensorflow/gradient_aggregation.py)
        if self.backward_passes_per_step > 1:
            tf = _tf()
            # accumulator variables / numpy sums need dense tensors, so
            # sparse grads densify here (the no-accumulation path keeps
            # them sparse via _sparse_reduce)
            grads = [tf.convert_to_tensor(g)
                     if isinstance(g, tf.IndexedSlices) else g
                     for g in grads]
            if not tf.executing_eagerly():
                return self._graph_accumulate_apply(tf, grads, tvars,
                                                    args, kwargs)
            gn = [_to_np(g) for g in grads]
            self._acc = gn if self._acc is None else \
                [a + b for a, b in zip(self._acc, gn)]
            self._pass += 1
            if self._pass < self.backward_passes_per_step:
                return None
            grads = [_from_np(a / self.backward_passes_per_step, g)
                     for a, g in zip(self._acc, grads)]
            self._acc, self._pass = None, 0
        grads = self._sync(grads, tvars)
        return self._opt.apply_gradients(zip(grads, tvars), *args, **kwargs)

    def _graph_accumulate_apply(self, tf, grads, tvars, args, kwargs):
        """tf.function-compatible accumulation: aggregation variables +
        tf.cond applying every k-th call (reference:
        ``gradient_aggregation.py`` graph-mode helper)."""
        k = self.backward_passes_per_step
        if self._agg_vars is None:
            with tf.init_scope():
                self._agg_vars = [
                    tf.Variable(tf.zeros(g.shape, g.dtype),
                                trainable=False) for g in grads]
                self._agg_counter = tf.Variable(0, dtype=tf.int64,
                                                trainable=False)
        assigns = [v.assign_add(g)
                   for v, g in zip(self._agg_vars, grads)]
        with tf.control_dependencies(assigns):
            count = self._agg_counter.assign_add(1)

        def apply_now():
            avg = [tf.cast(v.read_value(), g.dtype) / float(k)
                   for v, g in zip(self._agg_vars, grads)]
            synced = self._sync(avg, tvars)
            self._opt.apply_gradients(zip(synced, tvars), *args, **kwargs)
            resets = [v.assign(tf.zeros_like(v)) for v in self._agg_vars]
            with tf.control_dependencies(resets):
                return tf.constant(True)

        def skip():
            return tf.constant(False)

        return tf.cond(tf.equal(count % k, 0), apply_now, skip)


def DistributedOptimizer(optimizer, op: ReduceOp = Average,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         process_set: ProcessSet = global_process_set):
    """Reference factory (``tensorflow/__init__.py:627``)."""
    return _DistributedOptimizer(optimizer, op, compression,
                                 backward_passes_per_step, process_set)


from horovod_tpu.tensorflow.sync_batch_norm import (  # noqa: E402,F401
    SyncBatchNormalization)
from horovod_tpu.tensorflow import elastic  # noqa: E402,F401
