"""TF-side synchronized batch normalization.

Reference: ``horovod/tensorflow/sync_batch_norm.py`` (SyncBatchNormalization
subclassing keras BatchNormalization and allreducing the moments). This
adapter's TF path is host-side eager (models in migration; TPU compute is
JAX — see the package docstring), so the layer is a standalone
``tf.keras.layers.Layer`` that reduces moments through the eager collective
backend rather than hooking keras' private moment internals (which moved
between keras 2 and 3).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from horovod_tpu.common.basics import size
from horovod_tpu.common.process_sets import ProcessSet, global_process_set
from horovod_tpu.ops import collectives as _C
from horovod_tpu.ops.reduce_op import Average


def _tf():
    import tensorflow as tf
    return tf


def SyncBatchNormalization(axis: int = -1, momentum: float = 0.99,
                           epsilon: float = 1e-3, center: bool = True,
                           scale: bool = True,
                           process_set: ProcessSet = global_process_set,
                           name: Optional[str] = None):
    """Build a BatchNormalization layer whose training-time moments are
    averaged across the process set (reference behavior: per-rank moments
    allreduced so every replica normalizes with GLOBAL batch statistics)."""
    tf = _tf()

    class _SyncBatchNormalization(tf.keras.layers.Layer):
        def __init__(self) -> None:
            super().__init__(name=name)
            self.axis = axis
            self.momentum = momentum
            self.epsilon = epsilon
            self.center = center
            self.scale = scale
            self._process_set = process_set

        def build(self, input_shape):
            dim = int(input_shape[self.axis])
            self.gamma = self.add_weight(
                name="gamma", shape=(dim,), initializer="ones",
                trainable=self.scale)
            self.beta = self.add_weight(
                name="beta", shape=(dim,), initializer="zeros",
                trainable=self.center)
            self.moving_mean = self.add_weight(
                name="moving_mean", shape=(dim,), initializer="zeros",
                trainable=False)
            self.moving_variance = self.add_weight(
                name="moving_variance", shape=(dim,), initializer="ones",
                trainable=False)
            super().build(input_shape)

        def call(self, x, training=False):
            ndim = len(x.shape)
            ax = self.axis % ndim
            red = [d for d in range(ndim) if d != ax]
            if training:
                xf = tf.cast(x, tf.float32)
                mean = tf.reduce_mean(xf, axis=red)
                mean_sq = tf.reduce_mean(tf.square(xf), axis=red)
                if size() > 1:
                    # tf.py_function keeps this usable under tf.function
                    # (keras model.fit compiles train_step by default);
                    # the reduction itself is the host grouped allreduce
                    def _reduce(m, msq):
                        outs = _C.grouped_allreduce(
                            [m.numpy(), msq.numpy()], op=Average,
                            name=f"sbn.{self.name}",
                            process_set=self._process_set)
                        return (np.asarray(outs[0], np.float32),
                                np.asarray(outs[1], np.float32))

                    mean, mean_sq = tf.py_function(
                        _reduce, [mean, mean_sq],
                        [tf.float32, tf.float32])
                    mean.set_shape([x.shape[self.axis]])
                    mean_sq.set_shape([x.shape[self.axis]])
                var = mean_sq - tf.square(mean)
                # unbiased correction over the GLOBAL element count for the
                # running variance (matches reference torch SyncBatchNorm)
                n = int(np.prod([int(x.shape[d]) for d in red])) \
                    * max(self._process_set.size(), 1)
                corr = n / (n - 1) if n > 1 else 1.0
                self.moving_mean.assign(
                    self.momentum * self.moving_mean
                    + (1 - self.momentum) * mean)
                self.moving_variance.assign(
                    self.momentum * self.moving_variance
                    + (1 - self.momentum) * var * corr)
            else:
                mean = self.moving_mean
                var = self.moving_variance
            shape = [1] * ndim
            shape[ax] = -1
            mean = tf.reshape(mean, shape)
            var = tf.reshape(var, shape)
            gamma = tf.reshape(tf.cast(self.gamma, tf.float32), shape)
            beta = tf.reshape(tf.cast(self.beta, tf.float32), shape)
            y = (tf.cast(x, tf.float32) - mean) * tf.math.rsqrt(
                var + self.epsilon)
            return tf.cast(y * gamma + beta, x.dtype)

    return _SyncBatchNormalization()
