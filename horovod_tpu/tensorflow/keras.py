"""``horovod_tpu.tensorflow.keras`` — alias namespace for reference users
who import ``horovod.tensorflow.keras as hvd`` (reference:
``horovod/tensorflow/keras/__init__.py`` re-exports the same surface as
``horovod.keras`` built on the TF backend; here both namespaces are the
one Keras adapter)."""

from horovod_tpu.keras import *  # noqa: F401,F403
from horovod_tpu.keras import DistributedOptimizer, callbacks  # noqa: F401
from horovod_tpu.keras import elastic  # noqa: F401
