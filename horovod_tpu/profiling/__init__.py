"""Deep-profiling subsystem: evidence of *why*, captured exactly when
the cheap always-on layer says something is wrong.

Layers (docs/OBSERVABILITY.md "Deep profiling" / "Compile & memory
observability"):

* :mod:`horovod_tpu.profiling.manager` — bounded, step-windowed
  ``jax.profiler`` device traces (on demand, scheduled, or fired by the
  anomaly engine);
* :mod:`horovod_tpu.profiling.compile_watch` — compile-time metrics,
  tracing-cache misses, and the ``recompile_storm`` detector;
* :mod:`horovod_tpu.profiling.memory` — per-device HBM gauges + the
  ``hbm_growth`` slow-leak detector.

This package owns the two cross-cutting seams:

* the **step seam** — :func:`on_step_begin` / :func:`on_step_end`,
  called by :class:`horovod_tpu.train.callbacks.StepTimer` on every
  step (cheap no-ops unless a capture is pending/active or the HBM
  sampler has a backend that reports stats);
* the **anomaly seam** — :func:`on_anomaly`, called by the anomaly
  engine for every finding: when ``HVD_TPU_PROFILE_ON_ANOMALY`` is on
  (default), a finding arms a capture of the next
  ``HVD_TPU_PROFILE_STEPS`` steps and stamps the planned trace path
  into the finding itself, so the flight event, ``/metrics`` and the
  autopsy all point at the same evidence.

Also re-exported here: the device-annotation helpers the old
``horovod_tpu.utils.profiler`` stub used to hold (that module is now a
shim over this package).
"""

from __future__ import annotations

import contextlib
from typing import Any, Iterator, Optional

from horovod_tpu.profiling.manager import (ProfileManager, default_manager,
                                           profile_dir)
from horovod_tpu.profiling import compile_watch, memory

__all__ = [
    "ProfileManager", "default_manager", "profile_dir",
    "compile_watch", "memory",
    "on_step_begin", "on_step_end", "on_anomaly",
    "recent_captures", "finalize_open_capture", "reset",
    "start_trace", "stop_trace", "trace", "annotate", "annotate_fn",
]


# -- step seam (called from StepTimer; must never raise) ---------------------
def on_step_begin(step: int) -> None:
    try:
        default_manager().on_step_begin(step)
    except Exception:
        pass


def on_step_end(step: int) -> None:
    try:
        default_manager().on_step_end(step)
    except Exception:
        pass
    try:
        finding = memory.default_sampler().on_step(step)
        if finding is not None:
            from horovod_tpu.metrics.anomaly import report_finding
            report_finding(**finding)
    except Exception:
        pass


# -- anomaly seam (called from AnomalyEngine._flag) --------------------------
def on_anomaly(finding: dict) -> Optional[dict]:
    """A fresh anomaly finding: arm a rate-limited capture of the next
    K steps and stamp the planned path into the finding (the engine
    stores the same dict, so the path shows up in
    ``recent_findings()`` / the autopsy summary / the flight event)."""
    if finding.get("kind") == "world_changed":
        # a control-plane event, not a degradation: the re-mesh
        # timeline already measures recovery, a trace of the freshly
        # recompiling world would be pure noise, and burning the
        # rate-limited capture here would starve a REAL post-re-mesh
        # anomaly of its evidence
        return None
    from horovod_tpu.profiling.manager import on_anomaly_enabled
    if not on_anomaly_enabled():
        return None
    try:
        info = default_manager().request_capture(
            reason=f"anomaly:{finding.get('kind', 'unknown')}",
            trigger=finding, rate_limited=True)
    except Exception:
        return None
    if info is not None:
        finding["profile"] = info["path"]
    return info


# -- autopsy integration -----------------------------------------------------
def recent_captures() -> list:
    """Completed (and aborted-but-flushed) capture records — what the
    autopsy summary embeds under ``profiles``."""
    from horovod_tpu.profiling import manager as _m
    mgr = _m._MANAGER
    return mgr.recent_captures() if mgr is not None else []


def finalize_open_capture(reason: str = "aborted") -> Optional[dict]:
    """Close a mid-window capture NOW (autopsy/crash paths): a job that
    degraded, started its trace, and then hung still ships the trace."""
    from horovod_tpu.profiling import manager as _m
    mgr = _m._MANAGER
    return mgr.finalize_open_capture(reason) if mgr is not None else None


def reset() -> None:
    """Drop process-wide state so env is re-read (tests, elastic)."""
    from horovod_tpu.profiling import manager as _m
    _m.reset()
    memory.reset()
    compile_watch.reset_counts()


# -- device-annotation helpers (the old utils/profiler surface) --------------
def start_trace(log_dir: str) -> None:
    """Begin a device trace viewable in TensorBoard/XProf (the device
    -side counterpart of ``hvd.start_timeline``).  Prefer
    :class:`ProfileManager` for bounded, managed captures."""
    import jax
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    import jax
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named range on the device timeline (NVTX-range analog)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


def annotate_fn(name: Optional[str] = None):
    """Decorator form: ``@annotate_fn("allreduce.grads")``."""
    def deco(fn):
        label = name or fn.__name__

        def wrapped(*args: Any, **kwargs: Any):
            import jax
            with jax.profiler.TraceAnnotation(label):
                return fn(*args, **kwargs)
        return wrapped
    return deco
