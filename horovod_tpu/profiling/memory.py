"""HBM observability: per-device ``memory_stats()`` on the step seam.

TPU jobs rarely die AT the OOM — they die a thousand steps later, when
a slow host-side leak (a growing python-side cache, an accidental
device-array accumulation) or a rare large batch finally crosses the
line.  This module samples every local device's PJRT
``memory_stats()`` each ``HVD_TPU_HBM_SAMPLE_EVERY`` completed steps
(default 1 — the call is a cheap local read) and exports:

* ``hvd_hbm_bytes_in_use`` — worst (max) local device, merged ``max``
  across ranks;
* ``hvd_hbm_peak_bytes`` — worst peak so far (max merge);
* ``hvd_hbm_limit_bytes`` — smallest device limit (min merge);
* ``hvd_hbm_oom_margin_bytes`` — ``limit - peak`` of the tightest
  device, merged **min over ranks** by the fleet tree
  (docs/OBSERVABILITY.md "Fleet view") — ONE number for "how close is
  the whole job to an OOM";

plus an ``hbm_growth`` anomaly finding (via
:mod:`horovod_tpu.metrics.anomaly`) when in-use bytes grow
window-over-window for ``HVD_TPU_HBM_GROWTH_WINDOWS`` consecutive
windows — the slow-leak signature a threshold alert misses until it is
too late.

Devices whose backend reports no stats (CPU test meshes return
``None``) are skipped entirely: no gauges, no detector — absence of
data must not read as zero bytes free.  Tests inject a fake
``stats_fn``.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

DEFAULT_SAMPLE_EVERY = 1
DEFAULT_GROWTH_WINDOW = 20
DEFAULT_GROWTH_WINDOWS = 4
DEFAULT_GROWTH_MIN_FRAC = 0.01


def _envi(name: str, default: int) -> int:
    from horovod_tpu.common.config import env_int
    return env_int(name, default)


def _envf(name: str, default: float) -> float:
    from horovod_tpu.common.config import env_float
    return env_float(name, default)


def device_stats() -> Optional[List[dict]]:
    """One dict per local device that reports stats.  Returns ``[]``
    when every device CLEANLY reports no stats (a statless backend —
    CPU) and ``None`` when the read itself failed (a transient PJRT
    error must not be mistaken for "this backend never has stats")."""
    out: List[dict] = []
    errors = 0
    try:
        import jax
        for d in jax.local_devices():
            try:
                s = d.memory_stats()
            except Exception:
                errors += 1
                continue
            if s:
                out.append(dict(s))
    except Exception:
        return None
    if not out and errors:
        return None
    return out


def peak_bytes(stats: Optional[List[dict]] = None) -> Optional[int]:
    """Max ``peak_bytes_in_use`` over local devices (None when the
    backend reports nothing — CPU) — what ``bench.py`` records as
    ``hbm_peak_bytes``."""
    stats = (device_stats() or []) if stats is None else stats
    peaks = [s.get("peak_bytes_in_use") for s in stats
             if isinstance(s.get("peak_bytes_in_use"), (int, float))]
    return int(max(peaks)) if peaks else None


class HbmGrowthDetector:
    """Window-mean growth detector for slow leaks: consecutive windows
    whose mean in-use bytes each grow by at least ``min_frac`` over the
    previous window, ``windows`` times in a row, flag once per episode
    (a non-growing window re-arms)."""

    def __init__(self, window: Optional[int] = None,
                 windows: Optional[int] = None,
                 min_frac: Optional[float] = None) -> None:
        self.window = max(2, window or _envi("HBM_GROWTH_WINDOW",
                                             DEFAULT_GROWTH_WINDOW))
        self.windows = max(2, windows or _envi("HBM_GROWTH_WINDOWS",
                                               DEFAULT_GROWTH_WINDOWS))
        self.min_frac = min_frac if min_frac is not None else \
            _envf("HBM_GROWTH_MIN_FRAC", DEFAULT_GROWTH_MIN_FRAC)
        self._acc: List[float] = []
        self._prev_mean: Optional[float] = None
        self._first_mean: Optional[float] = None
        self._run = 0
        self._active = False

    def observe(self, bytes_in_use: float) -> Optional[dict]:
        self._acc.append(float(bytes_in_use))
        if len(self._acc) < self.window:
            return None
        mean = sum(self._acc) / len(self._acc)
        self._acc = []
        prev, self._prev_mean = self._prev_mean, mean
        if prev is None:
            self._first_mean = mean
            return None
        if mean > prev * (1.0 + self.min_frac):
            self._run += 1
        else:
            self._run = 0
            self._active = False
            self._first_mean = mean
        if self._active or self._run < self.windows:
            return None
        self._active = True
        base = self._first_mean or prev
        return {"kind": "hbm_growth",
                "bytes_in_use": int(mean),
                "baseline_bytes": int(base),
                "growth_ratio": round(mean / base, 4) if base else None,
                "windows": self._run,
                "window_steps": self.window}


class MemorySampler:
    """Step-seam sampler: refreshes the HBM gauges and feeds the growth
    detector.  ``stats_fn`` is injectable for tests (and for exotic
    backends); default reads every local jax device."""

    def __init__(self, registry=None,
                 stats_fn: Optional[Callable[[], List[dict]]] = None,
                 sample_every: Optional[int] = None) -> None:
        self._reg = registry
        self._stats_fn = stats_fn or device_stats
        self.sample_every = max(1, sample_every or _envi(
            "HBM_SAMPLE_EVERY", DEFAULT_SAMPLE_EVERY))
        self.detector = HbmGrowthDetector()
        self._n = 0
        self._lock = threading.Lock()
        self._dead = False  # backend reported no stats: stop asking
        self._seen_stats = False  # any sample ever carried stats

    def _registry(self):
        if self._reg is None:
            from horovod_tpu.metrics.registry import default_registry
            self._reg = default_registry()
        return self._reg

    def on_step(self, step: int) -> Optional[dict]:
        """Sample (subject to the stride); returns an ``hbm_growth``
        finding dict when the detector fired this sample (the caller —
        the profiling step hook — routes it to the anomaly engine)."""
        with self._lock:
            if self._dead:
                return None
            self._n += 1
            if (self._n - 1) % self.sample_every:
                return None
        stats = self._stats_fn()
        if stats is None:
            # the read failed (transient backend error): keep polling —
            # a bad first sample must not disable HBM observability for
            # the process lifetime
            return None
        if not stats:
            # clean contact with a statless backend (CPU): go quiet
            # forever instead of polling every step for nothing — but
            # only while NO sample has ever carried stats (a backend
            # that reported stats once is merely hiccuping)
            with self._lock:
                if not self._seen_stats:
                    self._dead = True
            return None
        with self._lock:
            self._seen_stats = True
        in_use = max(s.get("bytes_in_use", 0) for s in stats)
        peak = max(s.get("peak_bytes_in_use", 0) for s in stats)
        limits = [s.get("bytes_limit") for s in stats
                  if isinstance(s.get("bytes_limit"), (int, float))
                  and s.get("bytes_limit")]
        try:
            reg = self._registry()
            reg.gauge("hvd_hbm_bytes_in_use",
                      help="device bytes in use (worst local device)",
                      agg="max").set(float(in_use))
            reg.gauge("hvd_hbm_peak_bytes",
                      help="peak device bytes in use (worst local "
                           "device)",
                      agg="max").set(float(peak))
            if limits:
                limit = min(limits)
                reg.gauge("hvd_hbm_limit_bytes",
                          help="device memory limit (smallest local "
                               "device)",
                          agg="min").set(float(limit))
                margin = min(
                    float(s["bytes_limit"]) -
                    float(s.get("peak_bytes_in_use",
                                s.get("bytes_in_use", 0)))
                    for s in stats
                    if isinstance(s.get("bytes_limit"), (int, float))
                    and s.get("bytes_limit"))
                reg.gauge("hvd_hbm_oom_margin_bytes",
                          help="limit minus peak of the tightest "
                               "device; fleet-merged as min over ranks",
                          agg="min").set(margin)
        except Exception:
            pass
        return self.detector.observe(in_use)


_SAMPLER: Optional[MemorySampler] = None
_LOCK = threading.Lock()


def default_sampler() -> MemorySampler:
    global _SAMPLER
    if _SAMPLER is None:
        with _LOCK:
            if _SAMPLER is None:
                _SAMPLER = MemorySampler()
    return _SAMPLER


def reset() -> None:
    global _SAMPLER
    with _LOCK:
        _SAMPLER = None
