"""Compile observability: XLA compile time, tracing-cache misses, and
the ``recompile_storm`` detector.

The classic silent TPU perf killer is not a slow op — it is a *re*compile
storm: an input pipeline that drifts shapes (a ragged last batch, a
padding bug, a python-scalar hyperparameter traced as a constant) makes
``jit`` miss its tracing cache every step, and the job spends minutes in
XLA while the step-time metrics only show mush.  This module turns the
compiler into a first-class metrics source:

* ``hvd_compile_seconds{function=...}`` — per-function backend-compile
  time histogram (label set bounded; overflow lands on ``other``);
* ``hvd_compile_total`` — backend compilations;
* ``hvd_compile_cache_miss_total`` — tracing-cache misses (every
  "Compiling f" event: jit found no cached trace for the call);
* ``recompile_storm`` findings through the anomaly engine
  (:mod:`horovod_tpu.metrics.anomaly`) — the SAME function compiled
  more than ``HVD_TPU_RECOMPILE_STORM`` times past its
  ``HVD_TPU_RECOMPILE_WARMUP`` expected compiles, with the offending
  function named in the finding and the flight event (and, via the
  anomaly->profile hook, a device trace of the storm itself).

Sources (jax 0.4.x):

* ``jax.monitoring`` duration events
  (``/jax/core/compile/backend_compile_duration``) time the actual XLA
  backend compile;
* the ``jax_log_compiles`` log line ("Compiling <name> with global
  shapes...") names the function being compiled — jax's monitoring
  events carry no name, so the log record is the attribution channel.
  When this module enabled the flag itself it also stops those records
  propagating to the root logger (they become metrics, not stderr
  noise); a user who pre-enabled the flag keeps their output.

Everything degrades gracefully: if a jax upgrade renames the logger or
reshapes the message, compiles are still counted (monitoring events) —
only the per-function attribution goes to ``unknown``.
"""

from __future__ import annotations

import logging
import re
import threading
from typing import Dict, Optional

MAX_FUNCTION_LABELS = 32
DEFAULT_RECOMPILE_WARMUP = 2
DEFAULT_RECOMPILE_STORM = 3

# jax's lowering log line; the WARNING level is jax's own choice for
# log_compiles output (jax._src.interpreters.pxla)
_COMPILING_RE = re.compile(r"^Compiling ([^\s]+) with global shapes")
_PXLA_LOGGER = "jax._src.interpreters.pxla"
# also logs at WARNING under log_compiles ("Finished tracing...",
# "Finished XLA compilation...") — silenced alongside when WE own the
# flag, or every compile would print three stderr lines
_DISPATCH_LOGGER = "jax._src.dispatch"

_LOCK = threading.Lock()
_TLS = threading.local()

_installed = False
_handler: Optional[logging.Handler] = None
_null_handler: Optional[logging.Handler] = None
_we_enabled_flag = False
_prev_propagate: Dict[str, bool] = {}
_registry = None
# jax.monitoring has no listener removal, so the duration listener is
# registered at most once per process and gated on ``_installed`` —
# an uninstall/ensure_installed cycle must NOT add a second listener
# (every compile would count twice)
_listener_registered = False

# per-function compile counts + storm bookkeeping
_compiles: Dict[str, int] = {}
_flagged_at: Dict[str, int] = {}
_label_set: set = set()
_totals = {"compiles": 0, "cache_misses": 0, "seconds_total": 0.0}


def _envi(name: str, default: int) -> int:
    from horovod_tpu.common.config import env_int
    return env_int(name, default)


def enabled() -> bool:
    from horovod_tpu.common.config import env_bool
    return env_bool("COMPILE_METRICS", True)


def _reg():
    global _registry
    if _registry is None:
        from horovod_tpu.metrics.registry import default_registry
        _registry = default_registry()
    return _registry


def _function_label(name: str) -> str:
    """Bound the label cardinality: a storm of distinct names (e.g. a
    lambda per step) must not turn the registry into a leak."""
    with _LOCK:
        if name in _label_set:
            return name
        if len(_label_set) < MAX_FUNCTION_LABELS:
            _label_set.add(name)
            return name
    return "other"


def _note_compiling(name: str) -> None:
    """A tracing-cache miss for ``name`` (about to trace + compile)."""
    _TLS.last_name = name
    with _LOCK:
        _totals["cache_misses"] += 1
    try:
        _reg().counter(
            "hvd_compile_cache_miss_total",
            help="jit tracing-cache misses (each one traces and "
                 "compiles)").inc()
    except Exception:
        pass
    _check_storm(name)


def _check_storm(name: str) -> None:
    warmup = max(0, _envi("RECOMPILE_WARMUP", DEFAULT_RECOMPILE_WARMUP))
    storm = max(1, _envi("RECOMPILE_STORM", DEFAULT_RECOMPILE_STORM))
    with _LOCK:
        n = _compiles.get(name, 0) + 1
        if len(_compiles) < 4096 or name in _compiles:
            _compiles[name] = n
        recompiles = n - warmup
        last = _flagged_at.get(name, 0)
        if recompiles <= 0 or recompiles - last < storm:
            return
        _flagged_at[name] = recompiles
    # outside the lock: reporting fans out to counter + flight +
    # (possibly) a profile capture
    try:
        from horovod_tpu.metrics.anomaly import report_finding
        report_finding("recompile_storm", function=name, compiles=n,
                       recompiles=recompiles)
    except Exception:
        pass


def _on_backend_compile(seconds: float) -> None:
    name = getattr(_TLS, "last_name", None) or "unknown"
    with _LOCK:
        _totals["compiles"] += 1
        _totals["seconds_total"] += float(seconds)
    try:
        reg = _reg()
        reg.counter("hvd_compile_total",
                    help="XLA backend compilations").inc()
        reg.histogram(
            "hvd_compile_seconds",
            help="XLA backend compile time per compilation",
            labels={"function": _function_label(name)}).observe(seconds)
    except Exception:
        pass


class _CompileLogHandler(logging.Handler):
    def emit(self, record: logging.LogRecord) -> None:
        if not _installed:
            return
        try:
            m = _COMPILING_RE.match(record.getMessage())
            if m:
                _note_compiling(m.group(1))
        except Exception:
            pass  # observability must never break compilation


def ensure_installed(registry=None) -> bool:
    """Idempotent; returns True when the hooks are (already) live.
    Gated on ``HVD_TPU_COMPILE_METRICS`` (default on)."""
    global _installed, _handler, _null_handler, _we_enabled_flag, \
        _prev_propagate, _registry, _listener_registered
    if not enabled():
        return False
    with _LOCK:
        if _installed:
            return True
        _installed = True
    if registry is not None:
        _registry = registry
    try:
        import jax
        import jax.monitoring

        def _dur_listener(event: str, duration: float, **_kw) -> None:
            if _installed and \
                    event == "/jax/core/compile/backend_compile_duration":
                _on_backend_compile(duration)

        if not _listener_registered:
            jax.monitoring.register_event_duration_secs_listener(
                _dur_listener)
            _listener_registered = True
        lg = logging.getLogger(_PXLA_LOGGER)
        _handler = _CompileLogHandler(level=logging.DEBUG)
        lg.addHandler(_handler)
        if lg.level > logging.WARNING or lg.level == logging.NOTSET:
            lg.setLevel(logging.WARNING)
        if not jax.config.jax_log_compiles:
            jax.config.update("jax_log_compiles", True)
            _we_enabled_flag = True
            # we turned the firehose on; keep it out of stderr.  The
            # NullHandler matters: with propagate=False and NO handler,
            # stdlib logging falls back to the bare-format lastResort
            # stderr handler for WARNING records
            _null_handler = logging.NullHandler()
            for name in (_PXLA_LOGGER, _DISPATCH_LOGGER):
                lgr = logging.getLogger(name)
                _prev_propagate[name] = lgr.propagate
                lgr.propagate = False
                lgr.addHandler(_null_handler)
    except Exception as e:
        from horovod_tpu.common.logging import get_logger
        get_logger().warning("compile observability unavailable: %r", e)
    return True


def uninstall() -> None:
    """Tests only: disable the hooks and restore jax's flag/propagation.
    The monitoring listener stays registered (jax has no single-listener
    removal) but goes inert behind the ``_installed`` flag."""
    global _installed, _handler, _null_handler, _we_enabled_flag
    with _LOCK:
        if not _installed:
            return
        _installed = False
    lg = logging.getLogger(_PXLA_LOGGER)
    if _handler is not None:
        lg.removeHandler(_handler)
        _handler = None
    if _we_enabled_flag:
        try:
            import jax
            jax.config.update("jax_log_compiles", False)
        except Exception:
            pass
        for name, prop in _prev_propagate.items():
            lgr = logging.getLogger(name)
            lgr.propagate = prop
            if _null_handler is not None:
                lgr.removeHandler(_null_handler)
        _we_enabled_flag = False
        _prev_propagate.clear()
        _null_handler = None


def totals() -> dict:
    """Process-lifetime compile totals — what ``bench.py`` records as
    ``compile_seconds`` (measured backend-compile time, not the wall
    clock of a phase that also ran the first step)."""
    with _LOCK:
        return dict(_totals)


def per_function_compiles() -> Dict[str, int]:
    with _LOCK:
        return dict(_compiles)


def reset_counts() -> None:
    """Forget per-function storm bookkeeping, totals, and the label
    budget (tests, elastic re-init); the registry instruments are
    cumulative and stay.  Resetting the label set lets a fresh
    generation attribute ITS functions by name — without it a
    long-lived process saturates ``MAX_FUNCTION_LABELS`` once and every
    later function lands on ``other`` forever.  Re-used names attach to
    their existing series, so cardinality stays bounded per reset
    epoch."""
    with _LOCK:
        _compiles.clear()
        _flagged_at.clear()
        _label_set.clear()
        _totals.update({"compiles": 0, "cache_misses": 0,
                        "seconds_total": 0.0})
