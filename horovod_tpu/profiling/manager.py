"""ProfileManager: bounded, programmatic ``jax.profiler`` device traces.

The always-on observability layers are cheap by design (counters, a
bounded ring, EWMA detectors) and therefore can only say *that*
something is wrong.  The device trace is the tool that says *why* — but
it is far too heavy to leave running, and the moment someone thinks of
turning it on by hand the evidence is usually gone.  This manager makes
capture an *event*, not a mode: a capture is a **window measured in
steps**, opened at the next step boundary and closed after N completed
steps, with three drivers (docs/OBSERVABILITY.md "Deep profiling"):

* on demand — ``GET /debug/profile?steps=N`` on the worker exporter
  (multi-rank via ``HVD_TPU_PEER_HOSTS``, same addressing as the
  autopsy's peer fetch);
* scheduled — ``TelemetryCallback(profile_steps=N)`` captures the first
  N steps of training;
* **automatic** — the anomaly engine's findings
  (:mod:`horovod_tpu.metrics.anomaly`) trigger a capture of the next
  ``HVD_TPU_PROFILE_STEPS`` steps, so a job that degrades and then dies
  ships its own trace inside the autopsy bundle.

Bounded by construction: one capture at a time, anomaly-triggered
captures rate-limited to one per ``HVD_TPU_PROFILE_COOLDOWN_S``
(findings already carry per-episode hysteresis — together: one capture
per anomaly episode), and total retention under ``HVD_TPU_PROFILE_DIR``
size-rotated to ``HVD_TPU_PROFILE_MAX_BYTES`` (oldest captures deleted
first, the newest always kept).  Every completed capture lands as a
``profile_captured`` flight event, an ``hvd_profile_captures_total``
counter tick, and an entry the autopsy summary embeds.

TPU note: ``jax.profiler`` traces work identically on CPU (the test
mesh) and TPU; on TPU the capture contains the device-side XLA op
timeline XProf/TensorBoard render (the MLPerf TPU-pod analysis
methodology, arxiv 1909.09756).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

DEFAULT_PROFILE_STEPS = 5
DEFAULT_MAX_BYTES = 256 * 1024 * 1024
DEFAULT_COOLDOWN_S = 300.0
MAX_CAPTURE_RECORDS = 64


def profile_dir() -> str:
    """``HVD_TPU_PROFILE_DIR`` (default ``./hvd_profile`` — gitignored,
    like the autopsy dir).  Read live: elastic re-init and tests change
    env under a long-lived process."""
    from horovod_tpu.common.config import env_str
    return env_str("PROFILE_DIR") or os.path.join(os.getcwd(),
                                                  "hvd_profile")


def default_steps() -> int:
    from horovod_tpu.common.config import env_int
    return max(1, env_int("PROFILE_STEPS", DEFAULT_PROFILE_STEPS))


def on_anomaly_enabled() -> bool:
    from horovod_tpu.common.config import env_bool
    return env_bool("PROFILE_ON_ANOMALY", True)


def _env_float(name: str, default: float) -> float:
    from horovod_tpu.common.config import env_float
    return env_float(name, default)


def _env_int(name: str, default: int) -> int:
    from horovod_tpu.common.config import env_int
    return env_int(name, default)


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def _best_effort_rank() -> int:
    from horovod_tpu.diagnostics.flight_recorder import _best_effort_rank
    return _best_effort_rank()


class ProfileManager:
    """Step-windowed trace capture with retention and rate limiting.

    Thread-safe: requests arrive from the exporter's HTTP threads and
    the anomaly engine; the profiler itself is only started/stopped on
    the training thread via the :meth:`on_step_begin` /
    :meth:`on_step_end` seam (``jax.profiler`` is process-global and
    must not be toggled concurrently with the steps it measures).
    """

    def __init__(self, directory: Optional[str] = None,
                 registry=None) -> None:
        self._dir_opt = directory
        self._reg = registry
        self._lock = threading.Lock()
        self._pending: Optional[Dict[str, Any]] = None
        self._active: Optional[Dict[str, Any]] = None
        self._last_anomaly_capture = 0.0
        self.captures: List[dict] = []
        self.dropped_requests = 0

    # -- env/config -----------------------------------------------------------
    @property
    def directory(self) -> str:
        return self._dir_opt or profile_dir()

    def _registry(self):
        if self._reg is None:
            from horovod_tpu.metrics.registry import default_registry
            self._reg = default_registry()
        return self._reg

    # -- request side ---------------------------------------------------------
    def request_capture(self, steps: Optional[int] = None,
                        reason: str = "on_demand",
                        trigger: Optional[dict] = None,
                        rate_limited: bool = False) -> Optional[dict]:
        """Arm a capture of the next ``steps`` completed steps; returns
        the planned capture record (its ``path`` is where the trace will
        land) or ``None`` when refused (a capture is already pending /
        active, or — for ``rate_limited=True`` callers, the anomaly
        trigger — the cooldown has not elapsed)."""
        steps = int(steps) if steps else default_steps()
        if steps <= 0:
            return None
        now = time.time()
        with self._lock:
            if self._pending is not None or self._active is not None:
                self.dropped_requests += 1
                return None
            if rate_limited:
                cooldown = _env_float("PROFILE_COOLDOWN_S",
                                      DEFAULT_COOLDOWN_S)
                if now - self._last_anomaly_capture < cooldown:
                    self.dropped_requests += 1
                    return None
                # the cooldown is charged when the trace actually
                # STARTS (on_step_begin): a capture that fails to open
                # (unwritable dir, profiler busy) must not burn the
                # episode's only window for the next PROFILE_COOLDOWN_S
            rank = _best_effort_rank()
            path = os.path.join(
                self.directory,
                f"capture_{time.strftime('%Y%m%d_%H%M%S')}"
                f"_{int(now * 1000) % 1000:03d}_rank{rank}")
            self._pending = {"path": path, "steps": steps,
                            "reason": reason, "requested_at": now,
                            "trigger": trigger,
                            "rate_limited": bool(rate_limited)}
            return dict(self._pending)

    # -- step seam (training thread) ------------------------------------------
    def on_step_begin(self, step: int) -> None:
        with self._lock:
            req, self._pending = self._pending, None
            if req is None:
                return
            # claim the slot in the same critical section: between
            # consuming the request and starting the trace a concurrent
            # request_capture must still see the manager busy, or its
            # accepted capture (and a rate-limited caller's cooldown
            # credit) would be silently lost to "already tracing"
            req["first_step"] = int(step)
            req["remaining"] = req["steps"]
            req["started_at"] = time.time()
            req["started"] = False
            self._active = req
        try:
            os.makedirs(req["path"], exist_ok=True)
            self._start_trace(req["path"])
        except Exception as e:  # profiler busy / unwritable dir: degrade
            from horovod_tpu.common.logging import get_logger
            get_logger().warning("profile capture could not start: %r", e)
            with self._lock:
                if self._active is req:
                    self._active = None
            return
        with self._lock:
            if self._active is req:
                req["started"] = True
        if req.get("started") is not True:
            # finalize_open_capture/reset raced us between the claim
            # and the trace start and dropped the (then trace-less)
            # record: the capture is abandoned — close the trace we
            # just opened or the profiler runs unbounded forever and
            # every later capture fails with "already active"
            try:
                self._stop_trace()
            except Exception:
                pass
            return
        if req.get("rate_limited"):
            with self._lock:
                self._last_anomaly_capture = time.time()
        from horovod_tpu.common.logging import get_logger
        get_logger().info(
            "profiling the next %d step(s) into %s (%s)", req["steps"],
            req["path"], req["reason"])

    def on_step_end(self, step: int) -> None:
        with self._lock:
            act = self._active
            if act is None:
                return
            act["remaining"] -= 1
            if act["remaining"] > 0:
                return
            self._active = None
        self._finalize(act, last_step=int(step))

    def finalize_open_capture(self, reason: str = "aborted") -> Optional[dict]:
        """Close a capture whose window never completed (the job hung or
        is crashing): the autopsy calls this so a degrading-then-dead
        job still ships whatever trace it had open."""
        with self._lock:
            act, self._active = self._active, None
            self._pending = None
            if act is not None and not act.get("started", True):
                # claimed but the trace never opened (we raced
                # on_step_begin's start): nothing to flush — the
                # training thread detects the steal and closes the
                # trace itself
                return None
        if act is None:
            return None
        act["aborted"] = reason
        return self._finalize(act, last_step=None)

    # -- internals ------------------------------------------------------------
    def _start_trace(self, path: str) -> None:
        import jax
        jax.profiler.start_trace(path)

    def _stop_trace(self) -> None:
        import jax
        jax.profiler.stop_trace()

    def _finalize(self, act: dict, last_step: Optional[int]) -> dict:
        try:
            self._stop_trace()
        except Exception as e:
            from horovod_tpu.common.logging import get_logger
            get_logger().warning("profiler stop failed: %r", e)
        record = {
            "path": act["path"],
            "reason": act["reason"],
            "steps": act["steps"] - max(0, act.get("remaining", 0)),
            "first_step": act.get("first_step"),
            "last_step": last_step,
            "bytes": _dir_bytes(act["path"]),
            "seconds": round(time.time() - act.get("started_at",
                                                   time.time()), 3),
            "ts": round(time.time(), 3),
        }
        if act.get("trigger"):
            record["trigger"] = {k: v for k, v in act["trigger"].items()
                                 if k in ("kind", "function", "rank",
                                          "step", "category")}
        if act.get("aborted"):
            record["aborted"] = act["aborted"]
        retained = self._rotate(keep=act["path"])
        with self._lock:
            self.captures.append(record)
            del self.captures[:-MAX_CAPTURE_RECORDS]
        try:
            reg = self._registry()
            reg.counter("hvd_profile_captures_total",
                        help="completed device-trace captures").inc()
            reg.gauge("hvd_profile_retained_bytes",
                      help="bytes of trace captures retained under "
                           "the profile dir after rotation",
                      agg="max").set(float(retained))
        except Exception:
            pass
        try:
            from horovod_tpu.diagnostics.flight_recorder import record_event
            record_event("profile_captured", **{
                k: v for k, v in record.items() if k != "trigger"})
        except Exception:
            pass
        from horovod_tpu.common.logging import get_logger
        get_logger().info("profile captured: %s (%d bytes, %s)",
                          record["path"], record["bytes"],
                          record["reason"])
        return record

    def _rotate(self, keep: str) -> int:
        """Delete oldest capture dirs until total retention fits
        ``HVD_TPU_PROFILE_MAX_BYTES``; the just-written capture is never
        deleted (one over-budget capture beats zero evidence).  Returns
        retained bytes."""
        base = self.directory
        max_bytes = _env_int("PROFILE_MAX_BYTES", DEFAULT_MAX_BYTES)
        try:
            entries = []
            for name in os.listdir(base):
                p = os.path.join(base, name)
                if not os.path.isdir(p) or not name.startswith("capture_"):
                    continue
                entries.append((os.path.getmtime(p), p, _dir_bytes(p)))
        except OSError:
            return 0
        entries.sort()  # oldest first
        total = sum(b for _t, _p, b in entries)
        for _t, p, b in entries:
            if total <= max_bytes or os.path.abspath(p) == \
                    os.path.abspath(keep):
                continue
            try:
                shutil.rmtree(p)
                total -= b
                from horovod_tpu.common.logging import get_logger
                get_logger().info(
                    "profile retention: dropped %s (%d bytes)", p, b)
            except OSError:
                pass
        return total

    # -- introspection --------------------------------------------------------
    def recent_captures(self, last_n: int = MAX_CAPTURE_RECORDS) -> List[dict]:
        with self._lock:
            return [dict(c) for c in self.captures[-last_n:]]

    def status(self) -> dict:
        with self._lock:
            return {
                "dir": self.directory,
                "pending": dict(self._pending) if self._pending else None,
                "active": {k: v for k, v in self._active.items()
                           if k != "trigger"} if self._active else None,
                "captures": len(self.captures),
                "dropped_requests": self.dropped_requests,
            }


_MANAGER: Optional[ProfileManager] = None
_MANAGER_LOCK = threading.Lock()


def default_manager() -> ProfileManager:
    """The process-wide manager (created on first use; :func:`reset`
    drops it so tests / elastic re-init re-read env)."""
    global _MANAGER
    if _MANAGER is None:
        with _MANAGER_LOCK:
            if _MANAGER is None:
                _MANAGER = ProfileManager()
    return _MANAGER


def reset() -> None:
    global _MANAGER
    with _MANAGER_LOCK:
        m, _MANAGER = _MANAGER, None
    if m is not None:
        m.finalize_open_capture(reason="reset")
