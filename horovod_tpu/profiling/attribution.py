"""Roofline MFU attribution: decompose ``1 − MFU`` over the goodput
ledger's categories (ISSUE 16).

The identity.  With ``f_c`` the wall-share of ledger category ``c``
(``Σ_c f_c = 1`` — the ledger's closed-books invariant) and ``R`` the
compute-window roofline efficiency

    R = flops / (compute_seconds × peak_flops)
      = MFU / f_compute,

model-FLOPs utilization splits exactly:

    1 − MFU = Σ_{c ≠ compute} f_c  +  (1 − R) · f_compute.

The first term is time the device was not doing model math at all —
each addend is one ledger category, each with an existing tool
(exposed_comm → overlap/autotune, compile → recompile hunting,
checkpoint_stall → async tuning, ...; docs/TROUBLESHOOTING.md "My MFU
is low").  The second term — reported as ``kernel_inefficiency`` — is
the compute window itself running below the roofline: only a device
profile (XProf) can break it down further, which is why the
``goodput_regression`` detector arms exactly that capture.

On meshes where MFU is unknowable (CPU test meshes: no peak-FLOPs
table) the wall shares still stand on their own; ``mfu`` and
``kernel_inefficiency`` come back ``None`` — absence of a roofline
must not read as a perfect one.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from horovod_tpu.metrics.goodput import CATEGORIES


def attribute(goodput: Optional[Dict[str, Any]],
              mfu: Optional[float] = None,
              flops_per_step: Optional[float] = None,
              peak_flops: Optional[float] = None) -> Optional[Dict[str, Any]]:
    """Join a ledger account (``goodput.snapshot()`` or one closed
    window record — anything carrying ``wall_s`` + ``seconds``) with a
    measured MFU into the ``1 − MFU`` decomposition.

    ``mfu`` wins when given; otherwise it is derived from
    ``flops_per_step × steps / (wall × peak_flops)`` when all three are
    known.  Returns None when the ledger account itself is absent.
    """
    if not goodput:
        return None
    wall = float(goodput.get("wall_s") or 0.0)
    secs = goodput.get("seconds") or {}
    if wall <= 0.0 or not secs:
        return None
    shares = {c: float(secs.get(c, 0.0)) / wall for c in CATEGORIES}
    steps = goodput.get("steps")
    if steps is None:
        lw = goodput.get("last_window") or {}
        steps = lw.get("steps")
    if mfu is None and flops_per_step and peak_flops and steps:
        mfu = float(flops_per_step) * float(steps) / (wall * peak_flops)
    out: Dict[str, Any] = {
        "mfu": round(float(mfu), 4) if mfu is not None else None,
        "wall_s": round(wall, 4),
        "shares": {c: round(v, 4) for c, v in shares.items()},
        "one_minus_mfu": None,
        "kernel_inefficiency": None,
        "non_compute_share": round(1.0 - shares["compute"], 4),
        "dominating": _dominating(shares),
    }
    if mfu is not None:
        mfu = float(mfu)
        # (1 − R)·f_compute = f_compute − MFU exactly; a tiny negative
        # (measured MFU above the attributed compute share — clock skew
        # between the FLOPs window and the ledger window) clamps to 0
        # rather than crediting phantom efficiency
        out["one_minus_mfu"] = round(1.0 - mfu, 4)
        out["kernel_inefficiency"] = round(
            max(0.0, shares["compute"] - mfu), 4)
    return out


def _dominating(shares: Dict[str, float]) -> Optional[str]:
    loss = {c: v for c, v in shares.items() if c != "compute"}
    if not loss:
        return None
    return max(loss, key=loss.get)


def from_ledger(mfu: Optional[float] = None,
                flush_open: bool = False) -> Optional[Dict[str, Any]]:
    """Attribution over the live ledger's cumulative account; None when
    the ledger never ran (goodput disabled, no steps)."""
    try:
        from horovod_tpu.metrics import goodput as _gp
        snap = _gp.snapshot(flush_open=flush_open)
    except Exception:
        return None
    if snap is None:
        return None
    return attribute(snap, mfu=mfu)


def render_lines(att: Optional[Dict[str, Any]]) -> str:
    """One human-readable block (bench stdout, docs examples)."""
    if not att:
        return "mfu attribution: (no ledger data)"
    lines = []
    mfu = att.get("mfu")
    head = f"mfu={mfu:.3f}" if mfu is not None else "mfu=n/a"
    lines.append(f"mfu attribution ({head}, wall {att['wall_s']:.1f}s):")
    for c in CATEGORIES:
        lines.append(f"  {c:<17} {att['shares'].get(c, 0.0):7.2%}")
    ki = att.get("kernel_inefficiency")
    if ki is not None:
        lines.append(f"  {'kernel_inefficiency':<17} {ki:7.2%}")
    return "\n".join(lines)
