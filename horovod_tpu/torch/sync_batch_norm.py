"""Synchronous BatchNorm for the torch adapter.

Reference: ``horovod/torch/sync_batch_norm.py:40-218`` — normalize over the
GLOBAL batch by exchanging per-channel statistics in the forward pass, and
reduce ``sum_dy`` / ``sum_dy_xmu`` in the backward pass so input gradients
match single-process BN on the concatenated batch. The reference drives
CUDA-only kernels (``torch.batch_norm_stats`` etc.); here the math is plain
torch ops on host tensors (the adapter's domain), with the statistics
moved as ONE grouped allreduce instead of three allgathers.

Gradient contract (same as reference): ``grad_weight``/``grad_bias`` are
the LOCAL sums — the DistributedOptimizer's hook averaging handles their
reduction; only the statistics feeding ``grad_input`` are reduced here.
"""

from __future__ import annotations

import torch
from torch.autograd import Function
from torch.nn.modules.batchnorm import _BatchNorm

from horovod_tpu.common.basics import size
from horovod_tpu.ops.reduce_op import Sum


def _reduce_dims(x: torch.Tensor):
    return [0] + list(range(2, x.dim()))


class _SyncBatchNormFn(Function):
    @staticmethod
    def forward(ctx, x, weight, bias, running_mean, running_var, eps,
                momentum, track_running_stats):
        from horovod_tpu.torch import grouped_allreduce

        x = x.contiguous()
        dims = _reduce_dims(x)
        n_local = float(x.numel() // x.size(1))
        xd = x.double()
        local = [torch.tensor([n_local], dtype=torch.float64),
                 xd.sum(dims),
                 (xd * xd).sum(dims)]
        count_t, sum_x, sqsum_x = grouped_allreduce(
            local, op=Sum, name="sync_bn.stats")
        count = float(count_t.item())
        mean = (sum_x / count).to(x.dtype)
        var = (sqsum_x / count).to(x.dtype) - mean * mean
        invstd = torch.rsqrt(var.clamp_min(0) + eps)

        if track_running_stats and running_mean is not None:
            # unbiased var for the running estimate (reference applies the
            # count/(count-1) correction over the GLOBAL batch); momentum
            # arrives pre-resolved (CMA factor already substituted for
            # None by the module)
            unbiased = var * (count / max(count - 1.0, 1.0))
            m = momentum
            with torch.no_grad():
                running_mean.mul_(1 - m).add_(mean * m)
                running_var.mul_(1 - m).add_(unbiased * m)

        shape = [1, -1] + [1] * (x.dim() - 2)
        xhat = (x - mean.view(shape)) * invstd.view(shape)
        out = xhat
        if weight is not None:
            out = out * weight.view(shape)
        if bias is not None:
            out = out + bias.view(shape)
        ctx.save_for_backward(x, weight, mean, invstd)
        ctx.count = count
        return out

    @staticmethod
    def backward(ctx, dy):
        from horovod_tpu.torch import grouped_allreduce

        x, weight, mean, invstd = ctx.saved_tensors
        dy = dy.contiguous()
        dims = _reduce_dims(x)
        shape = [1, -1] + [1] * (x.dim() - 2)
        xmu = x - mean.view(shape)

        sum_dy_local = dy.sum(dims)
        sum_dy_xmu_local = (dy * xmu).sum(dims)

        # local grads for affine params (the optimizer reduces them)
        grad_weight = (sum_dy_xmu_local * invstd) \
            if (weight is not None and ctx.needs_input_grad[1]) else None
        grad_bias = sum_dy_local if ctx.needs_input_grad[2] else None

        grad_input = None
        if ctx.needs_input_grad[0]:
            sum_dy, sum_dy_xmu = grouped_allreduce(
                [sum_dy_local, sum_dy_xmu_local], op=Sum,
                name="sync_bn.grads")
            n = ctx.count
            w = weight.view(shape) if weight is not None else 1.0
            grad_input = (w * invstd.view(shape)) * (
                dy - (sum_dy / n).view(shape)
                - xmu * (invstd * invstd * sum_dy_xmu / n).view(shape))

        return (grad_input, grad_weight, grad_bias,
                None, None, None, None, None)


class SyncBatchNorm(_BatchNorm):
    """Drop-in for the reference's ``hvd.SyncBatchNorm`` on host tensors:
    training-mode statistics span the global batch across the process
    set's workers; eval mode uses the running estimates like plain BN."""

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D input)")

    def forward(self, input):
        self._check_input_dim(input)
        if self.training and self.track_running_stats \
                and self.num_batches_tracked is not None:
            self.num_batches_tracked = self.num_batches_tracked + 1

        # momentum=None means cumulative moving average (the _BatchNorm
        # contract): factor 1/num_batches_tracked
        if self.momentum is None:
            factor = 1.0 / float(max(int(self.num_batches_tracked or 1), 1))
        else:
            factor = self.momentum

        use_sync = self.training or not self.track_running_stats
        if not use_sync:
            return torch.nn.functional.batch_norm(
                input, self.running_mean, self.running_var, self.weight,
                self.bias, False, 0.0, self.eps)
        if size() == 1:
            return torch.nn.functional.batch_norm(
                input, self.running_mean, self.running_var, self.weight,
                self.bias, True, factor, self.eps)
        return _SyncBatchNormFn.apply(
            input, self.weight, self.bias, self.running_mean,
            self.running_var, self.eps, factor,
            self.track_running_stats)
