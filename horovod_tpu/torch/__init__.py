"""``horovod_tpu.torch`` — drop-in surface for reference PyTorch users.

Reference: ``horovod/torch/__init__.py`` + ``mpi_ops.py`` (:143-903) +
``optimizer.py`` (:35-590) + ``functions.py`` (:29-266). A user of the
reference's ``import horovod.torch as hvd`` can switch the import and keep
their script: eager collectives on ``torch.Tensor`` (CPU tensors — torch is
the host-side framework here; device compute belongs to JAX/XLA), the
gradient-hook DistributedOptimizer, and parameter/optimizer broadcast.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

# identity / lifecycle re-exports (reference: torch/mpi_ops.py:40-90)
from horovod_tpu.common.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, is_homogeneous, mpi_threads_supported,
    mpi_built, gloo_built, nccl_built, ccl_built, cuda_built, rocm_built,
    ddl_built, sycl_built, mpi_enabled, gloo_enabled,
    start_timeline, stop_timeline)
from horovod_tpu.common.process_sets import (  # noqa: F401
    ProcessSet, add_process_set, remove_process_set, global_process_set)
from horovod_tpu.ops.reduce_op import (  # noqa: F401
    Adasum, Average, Max, Min, Product, ReduceOp, Sum)
from horovod_tpu.ops import collectives as _C
from horovod_tpu.ops.backend import HvdHandle
from horovod_tpu.train.compression import Compression  # noqa: F401


def __getattr__(name):
    # lazy: SyncBatchNorm pulls in torch.nn at import time
    if name == "SyncBatchNorm":
        from horovod_tpu.torch.sync_batch_norm import SyncBatchNorm
        return SyncBatchNorm
    raise AttributeError(name)


def _torch():
    import torch
    return torch


def _to_np(tensor) -> np.ndarray:
    return tensor.detach().cpu().numpy()


def _from_np(arr, like) -> "Any":
    torch = _torch()
    return torch.from_numpy(np.ascontiguousarray(arr)).to(like.dtype)


class _TorchHandle:
    """Wraps an HvdHandle, converting results back to torch."""

    def __init__(self, handle: HvdHandle, like, post=None) -> None:
        self._h = handle
        self._like = like
        self._post = post

    def poll(self) -> bool:
        return self._h.poll()

    def wait(self, timeout: Optional[float] = None):
        out = self._h.wait(timeout)
        if self._post is not None:
            return self._post(out)
        return _from_np(np.asarray(out), self._like)


def allreduce_async(tensor, average: Optional[bool] = None,
                    name: Optional[str] = None,
                    op: Optional[ReduceOp] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set: ProcessSet = global_process_set):
    h = _C.allreduce_async(_to_np(tensor), average, name, op,
                           prescale_factor, postscale_factor, process_set)
    return _TorchHandle(h, tensor)


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, op: Optional[ReduceOp] = None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set: ProcessSet = global_process_set):
    return allreduce_async(tensor, average, name, op, prescale_factor,
                           postscale_factor, process_set).wait()


def allreduce_(tensor, average: Optional[bool] = None,
               name: Optional[str] = None, op: Optional[ReduceOp] = None,
               process_set: ProcessSet = global_process_set):
    """In-place variant (reference: ``allreduce_``)."""
    out = allreduce(tensor, average, name, op, process_set=process_set)
    tensor.copy_(out)
    return tensor


def allreduce_async_(tensor, average: Optional[bool] = None,
                     name: Optional[str] = None,
                     op: Optional[ReduceOp] = None,
                     prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0,
                     process_set: ProcessSet = global_process_set):
    """In-place async variant (reference: ``allreduce_async_``,
    ``torch/mpi_ops.py``): the handle's wait/synchronize copies the
    reduction back into ``tensor`` and returns it."""
    h = _C.allreduce_async(_to_np(tensor), average, name, op,
                           prescale_factor, postscale_factor, process_set)

    def post(out):
        tensor.copy_(_from_np(np.asarray(out), tensor))
        return tensor
    return _TorchHandle(h, tensor, post)


def grouped_allreduce_async(tensors, average: Optional[bool] = None,
                            name: Optional[str] = None,
                            op: Optional[ReduceOp] = None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set: ProcessSet = global_process_set):
    """One fused negotiation+program for the whole group (reference:
    ``grouped_allreduce_async``, ``torch/mpi_ops.py``)."""
    h = _C.grouped_allreduce_async([_to_np(t) for t in tensors], average,
                                   name, op, prescale_factor,
                                   postscale_factor, process_set)

    def post(outs):
        return [_from_np(np.asarray(o), t) for o, t in zip(outs, tensors)]
    return _TorchHandle(h, tensors, post)


def grouped_allreduce(tensors, average: Optional[bool] = None,
                      name: Optional[str] = None,
                      op: Optional[ReduceOp] = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set: ProcessSet = global_process_set):
    return grouped_allreduce_async(tensors, average, name, op,
                                   prescale_factor, postscale_factor,
                                   process_set).wait()


def grouped_allreduce_(tensors, average: Optional[bool] = None,
                       name: Optional[str] = None,
                       op: Optional[ReduceOp] = None,
                       prescale_factor: float = 1.0,
                       postscale_factor: float = 1.0,
                       process_set: ProcessSet = global_process_set):
    """In-place grouped variant (reference: ``grouped_allreduce_``)."""
    return grouped_allreduce_async_(tensors, average, name, op,
                                    prescale_factor, postscale_factor,
                                    process_set).wait()


def grouped_allreduce_async_(tensors, average: Optional[bool] = None,
                             name: Optional[str] = None,
                             op: Optional[ReduceOp] = None,
                             prescale_factor: float = 1.0,
                             postscale_factor: float = 1.0,
                             process_set: ProcessSet = global_process_set):
    """In-place async grouped variant (reference:
    ``grouped_allreduce_async_``)."""
    h = _C.grouped_allreduce_async([_to_np(t) for t in tensors], average,
                                   name, op, prescale_factor,
                                   postscale_factor, process_set)

    def post(outs):
        for t, o in zip(tensors, outs):
            t.copy_(_from_np(np.asarray(o), t))
        return tensors
    return _TorchHandle(h, tensors, post)


def allgather_async(tensor, name: Optional[str] = None,
                    process_set: ProcessSet = global_process_set):
    h = _C.allgather_async(_to_np(tensor), name, process_set)
    return _TorchHandle(h, tensor)


def allgather(tensor, name: Optional[str] = None,
              process_set: ProcessSet = global_process_set):
    return allgather_async(tensor, name, process_set).wait()


def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    process_set: ProcessSet = global_process_set):
    h = _C.broadcast_async(_to_np(tensor), root_rank, name, process_set)
    return _TorchHandle(h, tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              process_set: ProcessSet = global_process_set):
    return broadcast_async(tensor, root_rank, name, process_set).wait()


def broadcast_async_(tensor, root_rank: int, name: Optional[str] = None,
                     process_set: ProcessSet = global_process_set):
    """In-place async broadcast (reference: ``broadcast_async_``)."""
    h = _C.broadcast_async(_to_np(tensor), root_rank, name, process_set)

    def post(out):
        tensor.copy_(_from_np(np.asarray(out), tensor))
        return tensor
    return _TorchHandle(h, tensor, post)


def broadcast_(tensor, root_rank: int, name: Optional[str] = None,
               process_set: ProcessSet = global_process_set):
    out = broadcast(tensor, root_rank, name, process_set)
    tensor.copy_(out)
    return tensor


def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set: ProcessSet = global_process_set):
    """Async uneven alltoallv (reference: ``alltoall_async``,
    ``torch/mpi_ops.py:765``); wait returns the gathered tensor, plus the
    received splits ONLY when ``splits`` was supplied (the reference's
    return contract, ``torch/mpi_ops.py:817-846``)."""
    h = _C.alltoall_async(
        _to_np(tensor), None if splits is None else _to_np(splits)
        if hasattr(splits, "detach") else splits, name, process_set)

    def post(out):
        t, recv_splits = out
        gathered = _from_np(np.asarray(t), tensor)
        if splits is None:
            return gathered
        torch = _torch()
        return gathered, torch.from_numpy(np.asarray(recv_splits))
    return _TorchHandle(h, tensor, post)


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set: ProcessSet = global_process_set):
    return alltoall_async(tensor, splits, name, process_set).wait()


def sparse_allreduce_async(tensor, name: str, op: ReduceOp = None):
    """Allgather-based allreduce of a sparse COO tensor (reference:
    ``sparse_allreduce_async``, ``torch/mpi_ops.py:515-535``).

    Gathers every rank's indices and values; duplicate coordinates sum on
    coalesce, so the rebuilt sparse tensor is the elementwise reduction.
    Returns a zero-arg callable that completes the op (the reference's
    deferred-handle contract, consumed by the optimizer's synchronize).
    """
    torch = _torch()
    op = Average if op is None else op
    t = tensor.coalesce() if not tensor.is_coalesced() else tensor
    # dim 0 is the gather axis, so indices go [nnz, sparse_dim]
    idx_h = allgather_async(t._indices().transpose(0, 1).contiguous(),
                            name=f"{name}.indices")
    val_h = allgather_async(t._values(), name=f"{name}.values")

    def handle():
        values = val_h.wait()
        indices = idx_h.wait()
        if op == Average:
            values = values / size()
        if indices.numel() == 0 or values.numel() == 0:
            return torch.sparse_coo_tensor(
                torch.zeros((t.sparse_dim(), 0), dtype=torch.long),
                torch.zeros((0,) + t.shape[t.sparse_dim():],
                            dtype=t.dtype), t.size()).coalesce()
        return torch.sparse_coo_tensor(
            indices.transpose(0, 1).to(torch.long), values,
            t.size()).coalesce()

    return handle


def synchronize(handle):
    if callable(handle) and not hasattr(handle, "wait"):
        return handle()  # sparse_allreduce_async deferred handle
    return handle.wait()


def poll(handle) -> bool:
    return handle.poll()


def join(device: int = -1) -> int:
    return _C.join(device)


def barrier(process_set: ProcessSet = global_process_set) -> None:
    _C.barrier(process_set)


# -- parameter / optimizer broadcast (reference: torch/functions.py) --------

def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place broadcast of a state_dict or named_parameters iterable
    (reference: ``broadcast_parameters``, ``functions.py:29-68``)."""
    if hasattr(params, "items"):
        items = list(params.items())
    else:
        items = list(params)
    handles = [(name, broadcast_async(p, root_rank, name=f"bp.{name}"))
               for name, p in items if hasattr(p, "copy_")]
    for (name, h), (_, p) in zip(handles, [(n, p) for n, p in items
                                           if hasattr(p, "copy_")]):
        p.copy_(h.wait())


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Reference: ``broadcast_optimizer_state`` (``functions.py:116-266``)."""
    from horovod_tpu.train.optimizer import broadcast_object as _bo
    state = optimizer.state_dict()
    state = _bo(state, root_rank, name="opt_state")
    optimizer.load_state_dict(state)


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None):
    from horovod_tpu.train.optimizer import broadcast_object as _bo
    return _bo(obj, root_rank, name=name)


def allgather_object(obj, name: Optional[str] = None):
    """Reference: ``allgather_object`` (``torch/functions.py:233-266``)."""
    from horovod_tpu.train.optimizer import allgather_object as _ag
    return _ag(obj, name=name)


# -- DistributedOptimizer (reference: torch/optimizer.py) -------------------

class _DistributedOptimizer:
    """Wraps a torch optimizer: allreduce gradients before each step
    (reference: ``_DistributedOptimizer``, ``torch/optimizer.py:35-333``).

    HOOK MODE (default, needs torch >= 2.1): a post-accumulate-grad hook
    on every parameter enqueues its allreduce ASYNCHRONOUSLY the moment
    its gradient is final during ``.backward()`` — communication overlaps
    the rest of the backward pass, exactly the reference's
    grad-accumulator-hook design (``torch/optimizer.py:128-171``); the
    core's fusion buffer still coalesces the in-flight ops.
    ``synchronize()`` drains the handles. With
    ``backward_passes_per_step = k``, a parameter's hook counts down and
    enqueues on its k-th backward pass.

    FALLBACK (``HVD_TORCH_HOOKS=0``, older torch, or params without
    hooks): gradients are submitted in ``synchronize`` — same per-tensor
    names as the hooks would use (so mixed-mode ranks still negotiate),
    coalesced by the core's fusion buffer into one fused collective."""

    def __init__(self, optimizer, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1,
                 op: ReduceOp = Average,
                 process_set: ProcessSet = global_process_set) -> None:
        self._opt = optimizer
        self._compression = compression
        self._op = op
        self._process_set = process_set
        self.backward_passes_per_step = backward_passes_per_step
        self._synchronized = False
        if named_parameters is not None:
            self._names = {id(p): n for n, p in named_parameters}
        else:
            self._names = {}
        self._handles: Dict[int, tuple] = {}   # id(p) -> (p, handle, ctx)
        self._delay: Dict[int, int] = {}
        self._hook_handles: List[Any] = []
        self._use_hooks = (
            os.environ.get("HVD_TORCH_HOOKS", "1") != "0"
            and self._register_hooks())

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def _param_name(self, p, i: int, j: int) -> str:
        return self._names.get(id(p), f"grad.{i}.{j}")

    # -- hook plumbing ------------------------------------------------------
    def _register_hooks(self) -> bool:
        hooks = []
        for i, group in enumerate(self._opt.param_groups):
            for j, p in enumerate(group["params"]):
                if not p.requires_grad:
                    continue
                if not hasattr(p, "register_post_accumulate_grad_hook"):
                    for h in hooks:
                        h.remove()
                    return False  # torch < 2.1: fall back everywhere
                self._delay[id(p)] = self.backward_passes_per_step
                hooks.append(p.register_post_accumulate_grad_hook(
                    self._make_hook(i, j)))
        self._hook_handles = hooks
        return True

    def _make_hook(self, i: int, j: int):
        def hook(p):
            if self._delay[id(p)] <= 0:
                # reference raises the same way (optimizer.py:209-213):
                # a k+1-th backward would re-enqueue the tensor name
                # while the k-th op may still be in flight
                raise ValueError(
                    "Gradients were computed more than "
                    "backward_passes_per_step times before call to "
                    "step(). Increase backward_passes_per_step or call "
                    "synchronize() between backward passes.")
            self._delay[id(p)] -= 1
            if self._delay[id(p)] == 0:
                self._enqueue_async(p, i, j)
        return hook

    def _enqueue_async(self, p, i: int, j: int) -> None:
        """Fire this parameter's allreduce while backward continues.

        The submitted buffer is a PRIVATE COPY: the core reads it
        asynchronously, and ``p.grad``'s own memory can be mutated
        between backward and ``synchronize()`` (unscale, another
        accumulation) — a zero-copy view would race with that read."""
        if size() <= 1:
            return  # synchronize() applies the 1/k scale locally
        c, ctx = self._compression.compress(_to_np(p.grad))
        h = _C.allreduce_async(
            np.array(np.asarray(c), copy=True), average=None,
            name="torchgrad." + self._param_name(p, i, j), op=self._op,
            prescale_factor=1.0 / self.backward_passes_per_step,
            process_set=self._process_set)
        self._handles[id(p)] = (p, h, ctx)

    def synchronize(self) -> None:
        """Drain in-flight hook enqueues and reduce any remaining grads
        (reference: ``synchronize``, ``optimizer.py:249-292``). With
        ``backward_passes_per_step = k``, gradients are scaled by ``1/k``
        (the reference's TF aggregation helper divides the same way)."""
        params, names = [], []
        for i, group in enumerate(self._opt.param_groups):
            for j, p in enumerate(group["params"]):
                if p.grad is not None and id(p) not in self._handles:
                    params.append(p)
                    names.append(self._param_name(p, i, j))
        if size() <= 1:
            # keep the 1/k scale at EVERY world size so training dynamics
            # don't silently change between 1 and N processes
            if self.backward_passes_per_step > 1:
                for p in params:
                    p.grad.div_(self.backward_passes_per_step)
        else:
            # laggards (params whose hook never fired this cycle — unused
            # in the graph, hook-free mode, or mid-accumulation) submit
            # now with the SAME per-tensor names the hooks use, so a
            # param reduced via hook on one rank and here on another
            # still negotiates — and the core's fusion buffer coalesces
            # same-cycle submissions into one fused collective anyway
            late = []
            for p, name in zip(params, names):
                c, ctx = self._compression.compress(_to_np(p.grad))
                h = _C.allreduce_async(
                    np.array(np.asarray(c), copy=True), average=None,
                    name="torchgrad." + name, op=self._op,
                    prescale_factor=1.0 / self.backward_passes_per_step,
                    process_set=self._process_set)
                late.append((p, h, ctx))
            for p, h, ctx in list(self._handles.values()) + late:
                o = self._compression.decompress(np.asarray(h.wait()), ctx)
                p.grad.copy_(_from_np(np.asarray(o), p.grad))
        self._handles.clear()
        for key in self._delay:
            self._delay[key] = self.backward_passes_per_step
        self._synchronized = True

    def skip_synchronize(self):
        """Context manager marking gradients as already synchronized
        (reference: ``skip_synchronize``, ``torch/optimizer.py:294-312``).
        Kept for drop-in parity; this adapter's ``step()`` already skips
        the sync when ``synchronize()`` ran since the last step."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            self._synchronized = True
            yield
        return cm()

    def step(self, closure=None):
        """Synchronize (unless already done since the last step) and apply.

        One ``step()`` call ends a ``backward_passes_per_step``-backward
        accumulation cycle. In hook mode each parameter's allreduce was
        already enqueued during its k-th backward pass, so ``step()``
        just drains the in-flight handles (plus any laggards) and applies
        the update; in fallback mode all grads are submitted here. A
        manual ``synchronize()`` (e.g. for gradient clipping) is NOT
        repeated — where the reference warns and re-syncs unless wrapped
        in ``skip_synchronize()``, this adapter just skips the second
        sync."""
        if not self._synchronized:
            self.synchronize()
        self._synchronized = False
        return self._opt.step(closure)

    def zero_grad(self, *args: Any, **kwargs: Any):
        return self._opt.zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: ReduceOp = Average,
                         process_set: ProcessSet = global_process_set):
    """Factory (reference: ``DistributedOptimizer``, ``optimizer.py:506``)."""
    return _DistributedOptimizer(optimizer, named_parameters, compression,
                                 backward_passes_per_step, op, process_set)


# elastic surface: hvd.elastic.ElasticSampler / TorchState / run
# (reference: horovod/torch/elastic/{sampler,state}.py)
from horovod_tpu.torch import elastic  # noqa: E402,F401
