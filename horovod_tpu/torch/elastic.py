"""Elastic data sampling for the torch adapter.

Reference: ``horovod/torch/elastic/sampler.py`` (ElasticSampler) and
``horovod/torch/elastic/state.py`` (TorchState handlers). The sampler
partitions a dataset across the current world and — unlike a plain
DistributedSampler — tracks which indices were already processed this
epoch, so that after an elastic reset the *remaining* work is repartitioned
over the new world instead of being replayed.
"""

from __future__ import annotations

import math
import random
from typing import Iterator, List, Sequence

from horovod_tpu.common.basics import rank, size
from horovod_tpu.elastic import ObjectState, run  # noqa: F401 (re-export)


class ElasticSampler:
    """Rank-partitioning sampler with processed-index tracking.

    Usage contract (reference docstring, ``sampler.py:24-43``):

    1. include the sampler in the elastic ``State`` (its ``state_dict`` /
       ``load_state_dict`` round-trips through commit/restore),
    2. call :meth:`record_batch` (or :meth:`record_indices`) after each
       processed batch,
    3. call :meth:`set_epoch` at the END of each epoch to clear the
       processed set — calling it at the start would replay partial epochs.
    """

    def __init__(self, dataset, shuffle: bool = True, seed: int = 0) -> None:
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed

        self.epoch = 0
        self.processed_indices: set = set()

        self.num_replicas = 0
        self.rank = 0
        self.remaining_indices: List[int] = []
        self.num_samples = 0
        self.total_size = 0
        self.indices: List[int] = []

        self.reset()

    # -- epoch / progress tracking ------------------------------------------
    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self.processed_indices = set()
        self.reset()

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        self.record_indices(self.get_indices(batch_idx, batch_size))

    def record_indices(self, indices: Sequence[int]) -> None:
        self.processed_indices.update(indices)

    def get_indices(self, batch_idx: int, batch_size: int) -> List[int]:
        start = batch_idx * batch_size
        end = min(start + batch_size, len(self.indices))
        return self.indices[start:end]

    # -- elastic state ------------------------------------------------------
    def state_dict(self) -> dict:
        return dict(epoch=self.epoch,
                    processed_indices=set(self.processed_indices))

    def load_state_dict(self, state_dict: dict) -> None:
        self.epoch = state_dict["epoch"]
        self.processed_indices = set(state_dict["processed_indices"])
        self.reset()

    def reset(self) -> None:
        """Repartition the unprocessed indices over the CURRENT world
        (called after every elastic re-init)."""
        self.num_replicas = size()
        self.rank = rank()
        self.remaining_indices = [i for i in range(len(self.dataset))
                                  if i not in self.processed_indices]
        self.num_samples = int(
            math.ceil(len(self.remaining_indices) / max(self.num_replicas, 1)))
        self.total_size = self.num_samples * self.num_replicas

    # -- sampling -----------------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        self.indices = list(self.remaining_indices)
        if self.shuffle:
            # identical ordering on every rank (seed shared by contract)
            random.Random(self.seed + self.epoch).shuffle(self.indices)
        # pad to a multiple of the world size, then round-robin subsample.
        # Repeat as needed: with fewer remaining indices than ranks (late
        # elastic resume) a single self-copy is not enough — the reference
        # sampler crashes on its length assert here.
        if self.indices:
            while len(self.indices) < self.total_size:
                self.indices += self.indices[:(self.total_size
                                               - len(self.indices))]
        assert len(self.indices) == self.total_size
        self.indices = self.indices[self.rank:self.total_size:self.num_replicas]
        assert len(self.indices) == self.num_samples
        return iter(self.indices)

    def __len__(self) -> int:
        return self.num_samples


class TorchState(ObjectState):
    """Elastic state for torch training (reference:
    ``torch/elastic/state.py`` TorchState with Model/Optimizer/Sampler
    handlers): snapshots model + optimizer ``state_dict``s and sampler
    progress TOGETHER with the scalar attributes — one consistent unit for
    commit/restore, rank-0 broadcast sync, and (under the elastic driver)
    generation-restart persistence. ``name`` distinguishes concurrent
    states sharing a checkpoint dir.
    """

    def __init__(self, model=None, optimizer=None,
                 name: str = "torch_state", **kwargs) -> None:
        self._model = model
        self._optimizer = optimizer
        self._samplers = {k: v for k, v in kwargs.items()
                          if isinstance(v, ElasticSampler)}
        scalars = {k: v for k, v in kwargs.items()
                   if not isinstance(v, ElasticSampler)}
        super().__init__(name=name, torch_snaps=self._capture(), **scalars)
        # a prior generation's commit was loaded from the driver-managed
        # checkpoint — apply it to the live objects
        self._apply(self.torch_snaps)

    def _capture(self) -> dict:
        import copy
        # state_dict() aliases the live tensors — snapshot deep copies
        return dict(
            model={k: v.detach().clone() if hasattr(v, "detach")
                   else copy.deepcopy(v)
                   for k, v in self._model.state_dict().items()}
            if self._model is not None else None,
            optimizer=copy.deepcopy(self._optimizer.state_dict())
            if self._optimizer is not None else None,
            samplers={k: s.state_dict()
                      for k, s in self._samplers.items()})

    def _apply(self, snaps: dict) -> None:
        if self._model is not None and snaps.get("model"):
            self._model.load_state_dict(snaps["model"])
        if self._optimizer is not None and snaps.get("optimizer"):
            self._optimizer.load_state_dict(snaps["optimizer"])
        for k, s in self._samplers.items():
            snap = snaps.get("samplers", {}).get(k)
            if snap is not None:
                s.load_state_dict(snap)

    def save(self) -> None:
        self.torch_snaps = self._capture()
        super().save()

    def restore(self) -> None:
        super().restore()
        self._apply(self.torch_snaps)

    def sync(self) -> None:
        # rank 0's LIVE objects are the source of truth; ObjectState.sync
        # broadcasts the snapshot dict with the scalars in one object
        self.torch_snaps = self._capture()
        super().sync()
        self._apply(self.torch_snaps)

    def on_reset(self) -> None:
        for s in self._samplers.values():
            s.reset()
        super().on_reset()
