"""Pipeline parallelism (GPipe-style) over the ``pp`` mesh axis.

Absent from the reference (SURVEY.md §2.6). TPU-native design: all stages
run the same SPMD program under ``shard_map``; stage-to-stage transfer is a
``lax.ppermute`` ring shift of the activation; microbatches flow for
``M + S - 1`` ticks (fill + steady state + drain). Stage parameters are the
same pytree with a leading stage dim sharded over ``pp`` — so the schedule
is a compiled ``lax.scan``, with no host round-trips between ticks (the
whole pipeline is one XLA program; ICI transfers overlap with stage compute).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_spmd(stage_fn: Callable, stage_params, x_microbatches: jax.Array,
                  axis_name: str = "pp") -> jax.Array:
    """SPMD body (inside shard_map over ``axis_name``).

    stage_params: this stage's params — pytree, leaves ``[1, ...]`` (leading
    stage dim sharded to size 1 locally).
    x_microbatches: ``[M, mb, ...]`` all microbatches (stage 0 consumes them;
    other stages ignore).
    Returns ``[M, mb, ...]`` outputs (valid on every shard after the final
    cross-stage reduction).
    """
    S = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    my_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    mb_shape = x_microbatches.shape[1:]
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        act, ys = carry
        prev = lax.ppermute(act, axis_name, fwd_perm)
        feed = x_microbatches[jnp.clip(t, 0, M - 1)]
        cur = jnp.where(stage == 0, feed, prev)
        out = stage_fn(my_params, cur)
        emit = t - (S - 1)
        is_emit = (stage == S - 1) & (emit >= 0) & (emit < M)
        idx = jnp.clip(emit, 0, M - 1)
        ys = ys.at[idx].set(jnp.where(is_emit, out, ys[idx]))
        return (out, ys), None

    act0 = jnp.zeros(mb_shape, x_microbatches.dtype)
    ys0 = jnp.zeros((M,) + mb_shape, x_microbatches.dtype)
    (act, ys), _ = lax.scan(tick, (act0, ys0), jnp.arange(M + S - 1))
    # Only the last stage holds real outputs; replicate via masked psum.
    ys = jnp.where(stage == S - 1, ys, jnp.zeros_like(ys))
    return lax.psum(ys, axis_name)


def pipeline_apply(stage_fn: Callable, stage_params, x: jax.Array, mesh: Mesh,
                   n_microbatches: int, axis_name: str = "pp",
                   batch_axis: Optional[str] = "dp") -> jax.Array:
    """Array-level GPipe.

    stage_fn(params_for_one_stage, microbatch) -> microbatch (same shape).
    stage_params: pytree with leading dim = pp size, sharded over ``pp``.
    x: ``[T, ...]`` global batch; split into ``n_microbatches``.
    """
    from horovod_tpu.parallel.mesh import mesh_axis_size
    S = mesh_axis_size(mesh, axis_name)
    leading = {leaf.shape[0] for leaf in
               jax.tree_util.tree_leaves(stage_params)}
    if leading != {S}:
        raise ValueError(
            f"stage_params leading dims {sorted(leading)} must all equal the "
            f"'{axis_name}' mesh axis size ({S}); restack the stages for "
            f"this mesh (stage_stacked) instead of silently dropping some.")
    if S == 1:
        one = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        return stage_fn(one, x)
    T = x.shape[0]
    if T % n_microbatches != 0:
        raise ValueError(f"batch {T} not divisible by microbatches "
                         f"{n_microbatches}")
    xm = x.reshape((n_microbatches, T // n_microbatches) + x.shape[1:])
    b_ax = batch_axis if (batch_axis and mesh_axis_size(mesh, batch_axis) > 1) \
        else None
    x_spec = P(None, b_ax)
    out_spec = P(None, b_ax)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(axis_name), x_spec),
                       out_specs=out_spec, check_vma=False)
    def run(params_l, xm_l):
        return pipeline_spmd(stage_fn, params_l, xm_l, axis_name)

    ym = run(stage_params, xm)
    return ym.reshape((T,) + ym.shape[2:])


def stage_stacked(params_per_stage: list):
    """Stack a list of per-stage parameter pytrees into the leading-dim
    layout ``pipeline_apply`` expects (shard the result over ``pp``)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *params_per_stage)
