"""Pipeline parallelism (GPipe-style) over the ``pp`` mesh axis.

Absent from the reference (SURVEY.md §2.6). TPU-native design: all stages
run the same SPMD program under ``shard_map``; stage-to-stage transfer is a
``lax.ppermute`` ring shift of the activation; microbatches flow for
``M + S - 1`` ticks (fill + steady state + drain). Stage parameters are the
same pytree with a leading stage dim sharded over ``pp`` — so the schedule
is a compiled ``lax.scan``, with no host round-trips between ticks (the
whole pipeline is one XLA program; ICI transfers overlap with stage compute).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu._compat import axis_size, shard_map


def pipeline_spmd(stage_fn: Callable, stage_params, x_microbatches: jax.Array,
                  axis_name: str = "pp") -> jax.Array:
    """SPMD body (inside shard_map over ``axis_name``).

    stage_params: this stage's params — pytree, leaves ``[1, ...]`` (leading
    stage dim sharded to size 1 locally).
    x_microbatches: ``[M, mb, ...]`` all microbatches (stage 0 consumes them;
    other stages ignore).
    Returns ``[M, mb, ...]`` outputs (valid on every shard after the final
    cross-stage reduction).
    """
    S = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    my_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    mb_shape = x_microbatches.shape[1:]
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        act, ys = carry
        prev = lax.ppermute(act, axis_name, fwd_perm)
        feed = x_microbatches[jnp.clip(t, 0, M - 1)]
        cur = jnp.where(stage == 0, feed, prev)
        out = stage_fn(my_params, cur)
        emit = t - (S - 1)
        is_emit = (stage == S - 1) & (emit >= 0) & (emit < M)
        idx = jnp.clip(emit, 0, M - 1)
        ys = ys.at[idx].set(jnp.where(is_emit, out, ys[idx]))
        return (out, ys), None

    act0 = jnp.zeros(mb_shape, x_microbatches.dtype)
    ys0 = jnp.zeros((M,) + mb_shape, x_microbatches.dtype)
    (act, ys), _ = lax.scan(tick, (act0, ys0), jnp.arange(M + S - 1))
    # Only the last stage holds real outputs; replicate via masked psum.
    ys = jnp.where(stage == S - 1, ys, jnp.zeros_like(ys))
    return lax.psum(ys, axis_name)


def _pipeline_prep(stage_params, x: jax.Array, mesh: Mesh,
                   n_microbatches: int, axis_name: str,
                   batch_axis: Optional[str]):
    """Shared validation + microbatching for the array-level schedules:
    returns (S, xm, b_ax)."""
    from horovod_tpu.parallel.mesh import mesh_axis_size
    S = mesh_axis_size(mesh, axis_name)
    leading = {leaf.shape[0] for leaf in
               jax.tree_util.tree_leaves(stage_params)}
    if leading != {S}:
        raise ValueError(
            f"stage_params leading dims {sorted(leading)} must all equal the "
            f"'{axis_name}' mesh axis size ({S}); restack the stages for "
            f"this mesh (stage_stacked) instead of silently dropping some.")
    T = x.shape[0]
    if T % n_microbatches != 0:
        raise ValueError(f"batch {T} not divisible by microbatches "
                         f"{n_microbatches}")
    xm = x.reshape((n_microbatches, T // n_microbatches) + x.shape[1:])
    b_ax = batch_axis if (batch_axis and mesh_axis_size(mesh, batch_axis) > 1) \
        else None
    return S, xm, b_ax


def pipeline_apply(stage_fn: Callable, stage_params, x: jax.Array, mesh: Mesh,
                   n_microbatches: int, axis_name: str = "pp",
                   batch_axis: Optional[str] = "dp") -> jax.Array:
    """Array-level GPipe.

    stage_fn(params_for_one_stage, microbatch) -> microbatch (same shape).
    stage_params: pytree with leading dim = pp size, sharded over ``pp``.
    x: ``[T, ...]`` global batch; split into ``n_microbatches``.
    """
    S, xm, b_ax = _pipeline_prep(stage_params, x, mesh, n_microbatches,
                                 axis_name, batch_axis)
    if S == 1:
        one = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        return stage_fn(one, x)
    T = x.shape[0]
    x_spec = P(None, b_ax)
    out_spec = P(None, b_ax)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(axis_name), x_spec),
                       out_specs=out_spec, check_vma=False)
    def run(params_l, xm_l):
        return pipeline_spmd(stage_fn, params_l, xm_l, axis_name)

    ym = run(stage_params, xm)
    return ym.reshape((T,) + ym.shape[2:])


def stage_stacked(params_per_stage: list):
    """Stack a list of per-stage parameter pytrees into the leading-dim
    layout ``pipeline_apply`` expects (shard the result over ``pp``)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *params_per_stage)


# ---------------------------------------------------------------------------
# 1F1B-family schedule: eager backward with bounded activation memory
# ---------------------------------------------------------------------------

def pipeline_1f1b_spmd(stage_fn: Callable, loss_fn: Callable, stage_params,
                       x_microbatches: jax.Array, targets: jax.Array,
                       axis_name: str = "pp"):
    """Forward AND backward in one compiled schedule with backward starting
    as soon as each microbatch clears the last stage (1F1B family; GPipe
    runs all M forwards first, so its live-activation set grows with M).

    Memory: each stage stores only the INPUTS of its in-flight
    microbatches — a ring of ``min(2S-1, M)`` entries — and rematerializes
    the stage forward inside the backward tick (``jax.vjp``), the standard
    TPU recompute trade. GPipe-by-autodiff (differentiating
    :func:`pipeline_spmd`) keeps all ``M`` per-tick residuals live.

    Schedule (full tick t = one forward phase + one backward phase):
    stage s runs forward of microbatch ``t - s`` and backward of
    microbatch ``t - (2S - 2 - s)``; the last stage seeds the loss
    gradient in the same tick its forward completes. Total ticks:
    ``M + 2S - 2``.

    Returns ``(mean_loss, grads)`` where grads has this stage's parameter
    gradients (summed over microbatches, caller scales).
    """
    S = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    my_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    D = min(2 * S - 1, M)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]
    mb_shape = x_microbatches.shape[1:]

    def tick(carry, t):
        fwd_act, bwd_grad, in_buf, grad_acc, loss_acc = carry
        # ---- forward phase -------------------------------------------------
        prev = lax.ppermute(fwd_act, axis_name, fwd_perm)
        m_f = t - stage
        f_valid = (m_f >= 0) & (m_f < M)
        mf_c = jnp.clip(m_f, 0, M - 1)
        x_in = jnp.where(stage == 0, x_microbatches[mf_c], prev)
        out = stage_fn(my_params, x_in)
        slot_f = mf_c % D
        in_buf = in_buf.at[slot_f].set(
            jnp.where(f_valid, x_in, in_buf[slot_f]))
        # last stage: loss value + gradient seed for the SAME-tick backward
        tgt = targets[mf_c]
        loss_m, g_seed = jax.value_and_grad(
            lambda y: loss_fn(y, tgt))(out)
        loss_acc = loss_acc + jnp.where(
            (stage == S - 1) & f_valid, loss_m, 0.0)

        # ---- backward phase ------------------------------------------------
        g_in = lax.ppermute(bwd_grad, axis_name, bwd_perm)  # from s+1
        m_b = t - (2 * S - 2 - stage)
        b_valid = (m_b >= 0) & (m_b < M)
        mb_c = jnp.clip(m_b, 0, M - 1)
        x_b = in_buf[mb_c % D]
        g_out = jnp.where(stage == S - 1, g_seed, g_in)
        _, pullback = jax.vjp(stage_fn, my_params, x_b)  # remat forward
        g_params, g_x = pullback(g_out)
        grad_acc = jax.tree_util.tree_map(
            lambda a, g: a + jnp.where(b_valid, g, jnp.zeros_like(g)),
            grad_acc, g_params)
        bwd_next = jnp.where(b_valid, g_x, jnp.zeros_like(g_x))
        return (out, bwd_next, in_buf, grad_acc, loss_acc), None

    carry0 = (jnp.zeros(mb_shape, x_microbatches.dtype),
              jnp.zeros(mb_shape, x_microbatches.dtype),
              jnp.zeros((D,) + mb_shape, x_microbatches.dtype),
              jax.tree_util.tree_map(jnp.zeros_like, my_params),
              jnp.asarray(0.0, jnp.float32))
    (_, _, _, grads, loss_sum), _ = lax.scan(
        tick, carry0, jnp.arange(M + 2 * S - 2))
    # every shard returns the mean loss (only the last stage accumulated)
    mean_loss = lax.psum(loss_sum, axis_name) / M
    return mean_loss, grads


def pipeline_1f1b_apply(stage_fn: Callable, loss_fn: Callable, stage_params,
                        x: jax.Array, targets: jax.Array, mesh: Mesh,
                        n_microbatches: int, axis_name: str = "pp",
                        batch_axis: Optional[str] = "dp"):
    """Array-level 1F1B: returns ``(mean_loss, grads)`` with grads in the
    same stage-stacked layout as ``stage_params`` (per-microbatch-mean
    scale, matching ``jax.grad`` of the mean loss)."""
    S, xm, b_ax = _pipeline_prep(stage_params, x, mesh, n_microbatches,
                                 axis_name, batch_axis)
    T = x.shape[0]
    tm = targets.reshape((n_microbatches, T // n_microbatches)
                         + targets.shape[1:])
    if S == 1:
        one = jax.tree_util.tree_map(lambda p: p[0], stage_params)

        def total(p):
            losses = jax.vmap(lambda xb, tb: loss_fn(stage_fn(p, xb), tb))(
                xm, tm)
            return losses.mean()
        loss, g = jax.value_and_grad(total)(one)
        return loss, jax.tree_util.tree_map(lambda v: v[None], g)
    data_spec = P(None, b_ax)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(axis_name), data_spec, data_spec),
                       out_specs=(P(), P(axis_name)), check_vma=False)
    def run(params_l, xm_l, tm_l):
        loss, grads = pipeline_1f1b_spmd(stage_fn, loss_fn, params_l,
                                         xm_l, tm_l, axis_name)
        # per-microbatch mean -> same scale as jax.grad of the mean loss;
        # with a sharded batch axis the per-shard loss_fn already averaged
        # over local rows, so also average gradients across it
        grads = jax.tree_util.tree_map(lambda g: g[None] / n_microbatches,
                                       grads)
        if b_ax is not None:
            loss = lax.pmean(loss, b_ax)
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, b_ax), grads)
        return loss, grads

    return run(stage_params, xm, tm)
