"""Pipeline parallelism (GPipe / 1F1B / interleaved-1F1B) over ``pp``.

Absent from the reference (SURVEY.md §2.6). TPU-native design: all stages
run the same SPMD program under ``shard_map``; stage-to-stage transfer is a
``lax.ppermute`` ring shift of the activation; microbatches flow for
``M + S - 1`` ticks (fill + steady state + drain). Stage parameters are the
same pytree with a leading stage dim sharded over ``pp`` — so the schedule
is a compiled ``lax.scan``, with no host round-trips between ticks (the
whole pipeline is one XLA program; ICI transfers overlap with stage compute).

Schedule cost model (docs/PERF.md "Pipeline parallelism"): because the
program is SPMD, every device executes every tick's full body with
invalid units masked — masked compute costs the same time as real
compute. A combined forward+backward tick (the 1F1B family) therefore
pays the fill AND drain bubble on the combined tick cost, while
GPipe-by-autodiff pays each bubble once per pass; 1F1B's win on real
workloads is bounded activation memory (a ``min(2S-1, M)`` ring vs a
residual stack that grows with ``M``), and interleaved 1F1B's win is a
``~1/v`` smaller bubble at the same ``M``. The analytic tick counts are
exposed via :func:`schedule_ticks` / the ``ParallelPlan.bubble_fraction``
seam so benches and the autotuner can reason about them.

Gradient-correctness note (the ``replicate_from_stage`` helper): code
that differentiates a REPLICATED loss inside ``shard_map`` (with
``check_vma=False``) seeds one cotangent per shard; a plain masked
``lax.psum`` replication then delivers the SUM of those ``S`` identical
seeds to the source stage — every parameter reached through the psum
gets gradients scaled by ``S``. ``replicate_from_stage`` is the
differentiation-safe replication for that in-graph pattern: forward is
the masked psum, backward delivers the per-shard cotangent to the
source stage exactly once.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu._compat import axis_size, shard_map


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def replicate_from_stage(val, axis_name: str, src_stage: int):
    """Replicate ``val`` from shard ``src_stage`` of ``axis_name`` to all
    shards, safely differentiable from INSIDE ``shard_map``.

    Forward is the masked-psum idiom (zero every shard but the source,
    sum). Backward returns the incoming cotangent on the source shard
    and zeros elsewhere — NOT ``psum`` of the per-shard seeds, which is
    what a plain ``lax.psum`` transposes to under ``check_vma=False``
    and which over-counts a replicated consumer by the axis size (see
    module docstring)."""
    idx = lax.axis_index(axis_name)
    return lax.psum(jnp.where(idx == src_stage, val, jnp.zeros_like(val)),
                    axis_name)


def _replicate_fwd(val, axis_name, src_stage):
    return replicate_from_stage(val, axis_name, src_stage), None


def _replicate_bwd(axis_name, src_stage, _res, g):
    idx = lax.axis_index(axis_name)
    return (jnp.where(idx == src_stage, g, jnp.zeros_like(g)),)


replicate_from_stage.defvjp(_replicate_fwd, _replicate_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_cotangent(val, axis_name: str):
    """Identity forward; backward ``psum``\\ s the cotangent over
    ``axis_name``. Feed pipeline INPUTS through this when the producing
    computation is replicated over the pipeline axis (e.g. a replicated
    embedding): the input cotangent materializes only on the stage that
    consumes it (stage 0), and this replicates it so every shard's
    producer parameters see the same, correct gradient."""
    return val


def _psum_ct_fwd(val, axis_name):
    return val, None


def _psum_ct_bwd(axis_name, _res, g):
    return (lax.psum(g, axis_name),)


psum_cotangent.defvjp(_psum_ct_fwd, _psum_ct_bwd)


def pipeline_spmd(stage_fn: Callable, stage_params, x_microbatches: jax.Array,
                  axis_name: str = "pp") -> jax.Array:
    """SPMD body (inside shard_map over ``axis_name``).

    stage_params: this stage's params — pytree, leaves ``[1, ...]`` (leading
    stage dim sharded to size 1 locally).
    x_microbatches: ``[M, mb, ...]`` all microbatches (stage 0 consumes them;
    other stages ignore).
    Returns ``[M, mb, ...]`` outputs (valid on every shard after the final
    cross-stage reduction).
    """
    S = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    my_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    mb_shape = x_microbatches.shape[1:]
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        act, ys = carry
        prev = lax.ppermute(act, axis_name, fwd_perm)
        feed = x_microbatches[jnp.clip(t, 0, M - 1)]
        cur = jnp.where(stage == 0, feed, prev)
        out = stage_fn(my_params, cur)
        emit = t - (S - 1)
        is_emit = (stage == S - 1) & (emit >= 0) & (emit < M)
        idx = jnp.clip(emit, 0, M - 1)
        ys = ys.at[idx].set(jnp.where(is_emit, out, ys[idx]))
        return (out, ys), None

    act0 = jnp.zeros(mb_shape, x_microbatches.dtype)
    ys0 = jnp.zeros((M,) + mb_shape, x_microbatches.dtype)
    (act, ys), _ = lax.scan(tick, (act0, ys0), jnp.arange(M + S - 1))
    # Only the last stage holds real outputs; replicate to every shard.
    # replicate_from_stage (not a bare masked psum) keeps this schedule
    # correct under GPipe-by-autodiff — differentiating a replicated
    # loss inside shard_map otherwise scales every stage gradient by S
    # (see module docstring).
    return replicate_from_stage(ys, axis_name, S - 1)


def _pipeline_prep(stage_params, x: jax.Array, mesh: Mesh,
                   n_microbatches: int, axis_name: str,
                   batch_axis: Optional[str]):
    """Shared validation + microbatching for the array-level schedules:
    returns (S, xm, b_ax)."""
    from horovod_tpu.parallel.mesh import mesh_axis_size
    S = mesh_axis_size(mesh, axis_name)
    leading = {leaf.shape[0] for leaf in
               jax.tree_util.tree_leaves(stage_params)}
    if leading != {S}:
        raise ValueError(
            f"stage_params leading dims {sorted(leading)} must all equal the "
            f"'{axis_name}' mesh axis size ({S}); restack the stages for "
            f"this mesh (stage_stacked) instead of silently dropping some.")
    T = x.shape[0]
    if T % n_microbatches != 0:
        raise ValueError(f"batch {T} not divisible by microbatches "
                         f"{n_microbatches}")
    xm = x.reshape((n_microbatches, T // n_microbatches) + x.shape[1:])
    b_ax = batch_axis if (batch_axis and mesh_axis_size(mesh, batch_axis) > 1) \
        else None
    return S, xm, b_ax


def pipeline_apply(stage_fn: Callable, stage_params, x: jax.Array, mesh: Mesh,
                   n_microbatches: int, axis_name: str = "pp",
                   batch_axis: Optional[str] = "dp") -> jax.Array:
    """Array-level GPipe.

    stage_fn(params_for_one_stage, microbatch) -> microbatch (same shape).
    stage_params: pytree with leading dim = pp size, sharded over ``pp``.
    x: ``[T, ...]`` global batch; split into ``n_microbatches``.
    """
    S, xm, b_ax = _pipeline_prep(stage_params, x, mesh, n_microbatches,
                                 axis_name, batch_axis)
    if S == 1:
        one = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        return stage_fn(one, x)
    T = x.shape[0]
    x_spec = P(None, b_ax)
    out_spec = P(None, b_ax)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(axis_name), x_spec),
                       out_specs=out_spec, check_vma=False)
    def run(params_l, xm_l):
        return pipeline_spmd(stage_fn, params_l, xm_l, axis_name)

    ym = run(stage_params, xm)
    return ym.reshape((T,) + ym.shape[2:])


def stage_stacked(params_per_stage: list):
    """Stack a list of per-stage parameter pytrees into the leading-dim
    layout ``pipeline_apply`` expects (shard the result over ``pp``)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *params_per_stage)


# ---------------------------------------------------------------------------
# 1F1B-family schedule: eager backward with bounded activation memory
# ---------------------------------------------------------------------------

def pipeline_1f1b_spmd(stage_fn: Callable, loss_fn: Callable, stage_params,
                       x_microbatches: jax.Array, targets: jax.Array,
                       axis_name: str = "pp"):
    """Forward AND backward in one compiled schedule with backward starting
    as soon as each microbatch clears the last stage (1F1B family; GPipe
    runs all M forwards first, so its live-activation set grows with M).

    Memory: each stage stores only the INPUTS of its in-flight
    microbatches — a ring of ``min(2S-1, M)`` entries — and rematerializes
    the stage forward inside the backward tick (``jax.vjp``), the standard
    TPU recompute trade. GPipe-by-autodiff (differentiating
    :func:`pipeline_spmd`) keeps all ``M`` per-tick residuals live.

    Schedule (full tick t = one forward phase + one backward phase):
    stage s runs forward of microbatch ``t - s`` and backward of
    microbatch ``t - (2S - 2 - s)``; the last stage seeds the loss
    gradient in the same tick its forward completes. Total ticks:
    ``M + 2S - 2``.

    Returns ``(mean_loss, grads)`` where grads has this stage's parameter
    gradients (summed over microbatches, caller scales).
    """
    S = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    my_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    D = min(2 * S - 1, M)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]
    mb_shape = x_microbatches.shape[1:]

    def tick(carry, t):
        fwd_act, bwd_grad, in_buf, grad_acc, loss_acc = carry
        # ---- forward phase -------------------------------------------------
        prev = lax.ppermute(fwd_act, axis_name, fwd_perm)
        m_f = t - stage
        f_valid = (m_f >= 0) & (m_f < M)
        mf_c = jnp.clip(m_f, 0, M - 1)
        x_in = jnp.where(stage == 0, x_microbatches[mf_c], prev)
        out = stage_fn(my_params, x_in)
        slot_f = mf_c % D
        in_buf = in_buf.at[slot_f].set(
            jnp.where(f_valid, x_in, in_buf[slot_f]))
        # last stage: loss value + gradient seed for the SAME-tick backward
        tgt = targets[mf_c]
        loss_m, g_seed = jax.value_and_grad(
            lambda y: loss_fn(y, tgt))(out)
        loss_acc = loss_acc + jnp.where(
            (stage == S - 1) & f_valid, loss_m, 0.0)

        # ---- backward phase ------------------------------------------------
        g_in = lax.ppermute(bwd_grad, axis_name, bwd_perm)  # from s+1
        m_b = t - (2 * S - 2 - stage)
        b_valid = (m_b >= 0) & (m_b < M)
        mb_c = jnp.clip(m_b, 0, M - 1)
        x_b = in_buf[mb_c % D]
        g_out = jnp.where(stage == S - 1, g_seed, g_in)
        _, pullback = jax.vjp(stage_fn, my_params, x_b)  # remat forward
        g_params, g_x = pullback(g_out)
        grad_acc = jax.tree_util.tree_map(
            lambda a, g: a + jnp.where(b_valid, g, jnp.zeros_like(g)),
            grad_acc, g_params)
        bwd_next = jnp.where(b_valid, g_x, jnp.zeros_like(g_x))
        return (out, bwd_next, in_buf, grad_acc, loss_acc), None

    carry0 = (jnp.zeros(mb_shape, x_microbatches.dtype),
              jnp.zeros(mb_shape, x_microbatches.dtype),
              jnp.zeros((D,) + mb_shape, x_microbatches.dtype),
              jax.tree_util.tree_map(jnp.zeros_like, my_params),
              jnp.asarray(0.0, jnp.float32))
    (_, _, _, grads, loss_sum), _ = lax.scan(
        tick, carry0, jnp.arange(M + 2 * S - 2))
    # every shard returns the mean loss (only the last stage accumulated)
    mean_loss = lax.psum(loss_sum, axis_name) / M
    return mean_loss, grads


def _dp_reduce(grads, b_ax: Optional[str], dp_reducer: Optional[Callable]):
    """Reduce stage gradients over the data axis.

    ``dp_reducer`` is the composed-step seam (ISSUE 11 satellite): when
    given, it is called with the gradient pytree INSIDE ``shard_map``
    (the ``b_ax`` axis is live) and owns the mean-reduction — e.g.
    ``bucketed_grad_sync`` with buckets / hierarchical collectives /
    codecs / telemetry. The default is the exact-parity fallback: one
    dense ``lax.pmean`` per leaf."""
    if b_ax is None:
        return grads
    if dp_reducer is not None:
        return dp_reducer(grads)
    return jax.tree_util.tree_map(lambda g: lax.pmean(g, b_ax), grads)


def pipeline_1f1b_apply(stage_fn: Callable, loss_fn: Callable, stage_params,
                        x: jax.Array, targets: jax.Array, mesh: Mesh,
                        n_microbatches: int, axis_name: str = "pp",
                        batch_axis: Optional[str] = "dp",
                        dp_reducer: Optional[Callable] = None):
    """Array-level 1F1B: returns ``(mean_loss, grads)`` with grads in the
    same stage-stacked layout as ``stage_params`` (per-microbatch-mean
    scale, matching ``jax.grad`` of the mean loss).

    ``dp_reducer``: optional mean-reducer for the gradient pytree over
    the ``batch_axis`` (called inside ``shard_map``); defaults to the
    exact dense ``lax.pmean``. Pass the composed step's bucketed sync so
    dp gradient traffic stops bypassing bucketing/compression — see
    :func:`horovod_tpu.train.pipeline.make_pipeline_train_step`."""
    S, xm, b_ax = _pipeline_prep(stage_params, x, mesh, n_microbatches,
                                 axis_name, batch_axis)
    T = x.shape[0]
    tm = targets.reshape((n_microbatches, T // n_microbatches)
                         + targets.shape[1:])
    if S == 1:
        one = jax.tree_util.tree_map(lambda p: p[0], stage_params)

        def total(p):
            losses = jax.vmap(lambda xb, tb: loss_fn(stage_fn(p, xb), tb))(
                xm, tm)
            return losses.mean()
        loss, g = jax.value_and_grad(total)(one)
        return loss, jax.tree_util.tree_map(lambda v: v[None], g)
    data_spec = P(None, b_ax)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(axis_name), data_spec, data_spec),
                       out_specs=(P(), P(axis_name)), check_vma=False)
    def run(params_l, xm_l, tm_l):
        loss, grads = pipeline_1f1b_spmd(stage_fn, loss_fn, params_l,
                                         xm_l, tm_l, axis_name)
        # per-microbatch mean -> same scale as jax.grad of the mean loss;
        # with a sharded batch axis the per-shard loss_fn already averaged
        # over local rows, so also average gradients across it
        grads = jax.tree_util.tree_map(lambda g: g[None] / n_microbatches,
                                       grads)
        if b_ax is not None:
            loss = lax.pmean(loss, b_ax)
            grads = _dp_reduce(grads, b_ax, dp_reducer)
        return loss, grads

    return run(stage_params, xm, tm)


# ---------------------------------------------------------------------------
# Interleaved 1F1B: v virtual stage chunks per device (arxiv 2412.14374)
# ---------------------------------------------------------------------------

def _min_ring(intervals) -> int:
    """Smallest ring size R such that no two live intervals [a, c] whose
    keys collide mod R overlap (slot m%R must not be overwritten while
    its previous occupant is still unconsumed)."""
    if not intervals:
        return 1
    keys = sorted(intervals)
    for R in range(1, max(m for m, _, _ in keys) + 2):
        ok = True
        for i, (m1, a1, c1) in enumerate(keys):
            for (m2, a2, c2) in keys[i + 1:]:
                if m1 % R == m2 % R and a1 <= c2 and a2 <= c1:
                    ok = False
                    break
            if not ok:
                break
        if ok:
            return R
    return max(m for m, _, _ in keys) + 1


@functools.lru_cache(maxsize=256)
def interleaved_tables(S: int, v: int, M: int):
    """Static schedule tables for the interleaved 1F1B schedule.

    Stage ``q = j*S + d`` is virtual chunk ``j`` on device ``d`` (the
    standard interleaved placement: every stage-to-stage transfer is the
    same +1 ring shift, with the chunk index incrementing on the wrap).
    A greedy list scheduler assigns each device at most one forward and
    one backward unit per combined tick, forwards deepest-stage-first
    (drive the critical chain), backwards oldest-microbatch-first
    (drain the rings); the resulting tick count beats the plain-1F1B
    ``v*(M + 2S - 2)`` sub-tick equivalent for ``S > 2`` and equals it
    at ``S = 2``.

    Returns a dict of numpy tables (execution + receive-side, shape
    ``[T, S]``), ring sizes, the tick count ``T`` and the analytic
    bubble fraction ``1 - v*M/T``."""
    V = v * S
    ef, eb = {}, {}
    fw_rows, bw_rows = [], []
    t, done_f, done_b, total = 0, 0, 0, V * M
    limit = 4 * (V + M) * max(v, 1) + 64
    while (done_f < total or done_b < total) and t < limit:
        frow, brow = [], []
        for d in range(S):
            cands = []
            for j in range(v):
                q = j * S + d
                for m in range(M):        # microbatches in order per chunk
                    if (q, m) in ef:
                        continue
                    if q == 0 or ef.get((q - 1, m), limit) < t:
                        cands.append((j, m, q))
                    break
            if cands:
                j, m, q = max(cands, key=lambda c: (c[2], -c[1]))
                ef[(q, m)] = t
                done_f += 1
                frow.append((j, m, 1))
            else:
                frow.append((0, 0, 0))
        for d in range(S):
            cands = []
            for j in range(v):
                q = j * S + d
                for m in range(M):
                    if (q, m) in eb:
                        continue
                    if (q, m) not in ef or ef[(q, m)] > t:
                        continue
                    # last stage seeds its own backward the tick its
                    # forward lands (the fwd phase precedes the bwd
                    # phase inside one tick, like plain 1F1B)
                    if q == V - 1 or eb.get((q + 1, m), limit) < t:
                        cands.append((j, m, q))
                    break
            if cands:
                j, m, q = min(cands, key=lambda c: (c[1], -c[2]))
                eb[(q, m)] = t
                done_b += 1
                brow.append((j, m, 1))
            else:
                brow.append((0, 0, 0))
        fw_rows.append(frow)
        bw_rows.append(brow)
        t += 1
    if done_f != total or done_b != total:
        raise AssertionError(
            f"interleaved scheduler wedged at S={S} v={v} M={M} "
            f"({done_f}/{total} fwd, {done_b}/{total} bwd)")
    T = t

    # receive-side tables: what device d's incoming ppermute carries at
    # tick t (= the neighbour's unit from tick t-1) — derived here so no
    # indices ever travel on the wire
    fr = np.zeros((T, S, 3), np.int32)
    br = np.zeros((T, S, 3), np.int32)
    for tick in range(1, T):
        for d in range(S):
            s = (d - 1) % S
            j_s, m_s, ok = fw_rows[tick - 1][s]
            if ok and j_s * S + s != V - 1:
                fr[tick, d] = (j_s + (1 if s == S - 1 else 0), m_s, 1)
            s = (d + 1) % S
            j_s, m_s, ok = bw_rows[tick - 1][s]
            if ok and j_s * S + s != 0:
                br[tick, d] = (j_s - (1 if s == 0 else 0), m_s, 1)

    # ring capacities from the simulated live intervals
    act_live, store_live, grad_live, seed_live = [], [], [], []
    for (q, m), tf_ in ef.items():
        j, d = divmod(q, S)
        if q > 0:
            act_live.append((m, ef[(q - 1, m)] + 1, tf_))
        store_live.append((m, tf_, eb[(q, m)]))
        if q == V - 1:
            seed_live.append((m, tf_, eb[(q, m)]))
        if q < V - 1:
            grad_live.append((m, eb[(q + 1, m)] + 1, eb[(q, m)]))
    tables = {
        "fj": np.asarray([[u[0] for u in row] for row in fw_rows], np.int32),
        "fm": np.asarray([[u[1] for u in row] for row in fw_rows], np.int32),
        "fv": np.asarray([[u[2] for u in row] for row in fw_rows], np.int32),
        "bj": np.asarray([[u[0] for u in row] for row in bw_rows], np.int32),
        "bm": np.asarray([[u[1] for u in row] for row in bw_rows], np.int32),
        "bv": np.asarray([[u[2] for u in row] for row in bw_rows], np.int32),
        "frj": fr[:, :, 0], "frm": fr[:, :, 1], "frv": fr[:, :, 2],
        "brj": br[:, :, 0], "brm": br[:, :, 1], "brv": br[:, :, 2],
    }
    rings = {"act": _min_ring(act_live), "store": _min_ring(store_live),
             "grad": _min_ring(grad_live), "seed": _min_ring(seed_live)}
    return {"tables": tables, "rings": rings, "ticks": T,
            "bubble_fraction": 1.0 - (v * M) / T}


def pipeline_interleaved_spmd(stage_fn: Callable, loss_fn: Callable,
                              chunk_params, x_microbatches: jax.Array,
                              targets: jax.Array, v: int,
                              axis_name: str = "pp"):
    """Interleaved 1F1B (v virtual stage chunks per device), extending
    :func:`pipeline_1f1b_spmd`'s remat ring-buffer design.

    ``chunk_params``: this device's ``v`` chunks — pytree, leaves
    ``[v, ...]``; chunk ``j`` holds stage ``j*S + device``. Both
    directions of traffic are one ``ppermute`` per tick; which (chunk,
    microbatch) each payload belongs to is a STATIC schedule table
    (:func:`interleaved_tables`), so only activations travel. Each
    stage stores only the inputs of its in-flight microbatches (per-
    chunk rings) and rematerializes the chunk forward inside the
    backward phase, exactly like plain 1F1B — the bubble shrinks
    because a microbatch finishes a 1/v-sized chunk per tick, so fill
    and drain cost ``~1/v`` of a full device stage each.

    Returns ``(mean_loss, chunk_grads)`` with grads summed over
    microbatches (caller scales), leaves ``[v, ...]``."""
    S = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    sched = interleaved_tables(S, int(v), M)
    tb = {k: jnp.asarray(a) for k, a in sched["tables"].items()}
    rings = sched["rings"]
    T = sched["ticks"]
    mb_shape = x_microbatches.shape[1:]
    dtype = x_microbatches.dtype
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]

    def tick(carry, t):
        (fwd_pay, bwd_pay, fwd_in, bwd_in, in_store, seed_buf,
         grad_acc, loss_acc) = carry
        # ---- receive: neighbours' tick t-1 payloads -----------------------
        f_act = lax.ppermute(fwd_pay, axis_name, fwd_perm)
        g_act = lax.ppermute(bwd_pay, axis_name, bwd_perm)
        frj, frm = tb["frj"][t, stage], tb["frm"][t, stage]
        frv = tb["frv"][t, stage] == 1
        fwd_in = fwd_in.at[frj, frm % rings["act"]].set(
            jnp.where(frv, f_act, fwd_in[frj, frm % rings["act"]]))
        brj, brm = tb["brj"][t, stage], tb["brm"][t, stage]
        brv = tb["brv"][t, stage] == 1
        bwd_in = bwd_in.at[brj, brm % rings["grad"]].set(
            jnp.where(brv, g_act, bwd_in[brj, brm % rings["grad"]]))

        # ---- forward phase ------------------------------------------------
        j, m = tb["fj"][t, stage], tb["fm"][t, stage]
        f_valid = tb["fv"][t, stage] == 1
        is_q0 = (stage == 0) & (j == 0)
        x_in = jnp.where(is_q0, x_microbatches[m],
                         fwd_in[j, m % rings["act"]])
        p_j = jax.tree_util.tree_map(lambda p: p[j], chunk_params)
        out = stage_fn(p_j, x_in)
        in_store = in_store.at[j, m % rings["store"]].set(
            jnp.where(f_valid, x_in, in_store[j, m % rings["store"]]))
        # last stage: loss value + same-tick gradient seed
        is_lastq = (stage == S - 1) & (j == v - 1)
        loss_m, g_seed = jax.value_and_grad(
            lambda y: loss_fn(y, targets[m]))(out)
        loss_acc = loss_acc + jnp.where(is_lastq & f_valid, loss_m, 0.0)
        seed_buf = seed_buf.at[m % rings["seed"]].set(
            jnp.where(is_lastq & f_valid, g_seed,
                      seed_buf[m % rings["seed"]]))
        fwd_pay = out  # receivers mask by their own table row

        # ---- backward phase -----------------------------------------------
        jb, mb = tb["bj"][t, stage], tb["bm"][t, stage]
        b_valid = tb["bv"][t, stage] == 1
        is_lastq_b = (stage == S - 1) & (jb == v - 1)
        g_out = jnp.where(is_lastq_b, seed_buf[mb % rings["seed"]],
                          bwd_in[jb, mb % rings["grad"]])
        x_b = in_store[jb, mb % rings["store"]]
        p_b = jax.tree_util.tree_map(lambda p: p[jb], chunk_params)
        _, pullback = jax.vjp(stage_fn, p_b, x_b)   # remat chunk forward
        g_params, g_x = pullback(g_out)
        grad_acc = jax.tree_util.tree_map(
            lambda a, g: a.at[jb].add(
                jnp.where(b_valid, g, jnp.zeros_like(g))),
            grad_acc, g_params)
        bwd_pay = jnp.where(b_valid, g_x, jnp.zeros_like(g_x))
        return (fwd_pay, bwd_pay, fwd_in, bwd_in, in_store, seed_buf,
                grad_acc, loss_acc), None

    zeros_mb = jnp.zeros(mb_shape, dtype)
    carry0 = (
        zeros_mb, zeros_mb,
        jnp.zeros((v, rings["act"]) + mb_shape, dtype),
        jnp.zeros((v, rings["grad"]) + mb_shape, dtype),
        jnp.zeros((v, rings["store"]) + mb_shape, dtype),
        jnp.zeros((rings["seed"],) + mb_shape, dtype),
        jax.tree_util.tree_map(jnp.zeros_like, chunk_params),
        jnp.asarray(0.0, jnp.float32),
    )
    (_, _, _, _, _, _, grads, loss_sum), _ = lax.scan(
        tick, carry0, jnp.arange(T))
    mean_loss = lax.psum(loss_sum, axis_name) / M
    return mean_loss, grads


def pipeline_interleaved_apply(stage_fn: Callable, loss_fn: Callable,
                               stage_params, x: jax.Array,
                               targets: jax.Array, mesh: Mesh,
                               n_microbatches: int, virtual_stages: int = 2,
                               axis_name: str = "pp",
                               batch_axis: Optional[str] = "dp",
                               dp_reducer: Optional[Callable] = None):
    """Array-level interleaved 1F1B.

    ``stage_params``: pytree with leading dim ``V = virtual_stages * S``
    in stage order (stage ``q`` is chunk ``q // S`` on device ``q % S``).
    Returns ``(mean_loss, grads)`` in the same stage-stacked layout,
    per-microbatch-mean scale (matching ``jax.grad`` of the mean loss).
    ``dp_reducer`` as in :func:`pipeline_1f1b_apply`."""
    from horovod_tpu.parallel.mesh import mesh_axis_size
    v = int(virtual_stages)
    if v < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {v}")
    S = mesh_axis_size(mesh, axis_name)
    V = v * S
    leading = {leaf.shape[0] for leaf in
               jax.tree_util.tree_leaves(stage_params)}
    if leading != {V}:
        raise ValueError(
            f"stage_params leading dims {sorted(leading)} must all equal "
            f"virtual_stages * {axis_name} size = {V}")
    T = x.shape[0]
    if T % n_microbatches != 0:
        raise ValueError(f"batch {T} not divisible by microbatches "
                         f"{n_microbatches}")
    xm = x.reshape((n_microbatches, T // n_microbatches) + x.shape[1:])
    tm = targets.reshape((n_microbatches, T // n_microbatches)
                         + targets.shape[1:])
    b_ax = batch_axis if (batch_axis and mesh_axis_size(mesh, batch_axis) > 1) \
        else None
    if S == 1:
        one_chunks = stage_params  # [V, ...]: all chunks local

        def total(pl):
            def one_mb(xb, tb_):
                h = xb
                for q in range(V):
                    h = stage_fn(jax.tree_util.tree_map(
                        lambda p, q=q: p[q], pl), h)
                return loss_fn(h, tb_)
            return jax.vmap(one_mb)(xm, tm).mean()
        loss, g = jax.value_and_grad(total)(one_chunks)
        return loss, g

    # stage q = j*S + d  ->  device-major layout [S, v, ...] so the pp
    # shards receive their own v chunks
    def to_device_major(p):
        return jnp.moveaxis(
            p.reshape((v, S) + p.shape[1:]), 1, 0)

    def from_device_major(p):
        return jnp.moveaxis(p, 0, 1).reshape((V,) + p.shape[2:])

    dm_params = jax.tree_util.tree_map(to_device_major, stage_params)
    data_spec = P(None, b_ax)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(axis_name), data_spec, data_spec),
                       out_specs=(P(), P(axis_name)), check_vma=False)
    def run(params_l, xm_l, tm_l):
        chunks = jax.tree_util.tree_map(lambda p: p[0], params_l)
        loss, grads = pipeline_interleaved_spmd(
            stage_fn, loss_fn, chunks, xm_l, tm_l, v, axis_name)
        grads = jax.tree_util.tree_map(
            lambda g: g[None] / n_microbatches, grads)
        if b_ax is not None:
            loss = lax.pmean(loss, b_ax)
            grads = _dp_reduce(grads, b_ax, dp_reducer)
        return loss, grads

    loss, dm_grads = run(dm_params, xm, tm)
    return loss, jax.tree_util.tree_map(from_device_major, dm_grads)


def schedule_ticks(schedule: str, S: int, M: int, v: int = 1):
    """Analytic (ticks, ideal_ticks) for one training step of a
    schedule, in that schedule's own tick units (a combined
    forward+backward tick for the 1F1B family; forward-pass + transposed
    backward-pass tick-slots for GPipe-by-autodiff). ``1 - ideal/ticks``
    is the pipeline bubble fraction the bench artifact records."""
    if S <= 1:
        return max(M, 1), max(M, 1)
    if schedule == "gpipe":
        return 2 * (M + S - 1), 2 * M
    if schedule == "1f1b":
        return M + 2 * S - 2, M
    if schedule == "interleaved":
        sched = interleaved_tables(S, max(int(v), 1), M)
        return sched["ticks"], v * M
    raise ValueError(f"unknown schedule {schedule!r}; expected "
                     "gpipe | 1f1b | interleaved")


def bubble_fraction(schedule: str, S: int, M: int, v: int = 1) -> float:
    """Analytic fill+drain bubble fraction for ``schedule`` at pipeline
    depth ``S``, ``M`` microbatches, ``v`` virtual chunks per device."""
    ticks, ideal = schedule_ticks(schedule, S, M, v)
    return 1.0 - ideal / ticks
