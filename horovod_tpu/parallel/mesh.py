"""Device-mesh construction and sharding rules — the TPU data plane.

There is no reference analog: the reference's data plane is NCCL rings
(``horovod/common/ops/nccl_operations.cc``). On TPU the equivalent of "create a
NCCL communicator per (process set, device map, stream)"
(``nccl_operations.cc:65-107``) is "build a named `jax.sharding.Mesh` per
process set and let XLA place collectives on ICI/DCN". This module owns the
axis conventions used across the framework:

==========  =========================================  ==================
axis name   parallelism                                collective traffic
==========  =========================================  ==================
``dp``      data parallel (gradient reduction)          psum / reduce_scatter
``pp``      pipeline parallel (stage to stage)          ppermute
``ep``      expert parallel (MoE token dispatch)        all_to_all
``sp``      sequence/context parallel (ring attention,  ppermute / all_to_all
            Ulysses)
``tp``      tensor parallel (sharded matmuls)           psum / all_gather
==========  =========================================  ==================

Axis order is chosen so that ``tp`` (highest bandwidth need, per-layer
collectives) maps to the innermost — most tightly ICI-coupled — devices, and
``dp`` to the outermost (can ride DCN across slices), following the standard
TPU scaling recipe (jax-ml scaling book).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis order, outermost → innermost.
AXIS_ORDER: Tuple[str, ...] = ("dp", "pp", "ep", "sp", "tp")

DATA_AXIS = "dp"
PIPELINE_AXIS = "pp"
EXPERT_AXIS = "ep"
SEQUENCE_AXIS = "sp"
TENSOR_AXIS = "tp"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape. ``-1`` for at most one axis means "absorb all
    remaining devices" (conventionally ``dp``)."""

    dp: int = -1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {"dp": self.dp, "pp": self.pp, "ep": self.ep,
                 "sp": self.sp, "tp": self.tp}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"At most one axis may be -1, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product "
                    f"{fixed} ({sizes})")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"Mesh spec {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def build_mesh(spec: Optional[MeshSpec] = None,
               devices: Optional[Sequence[jax.Device]] = None,
               **axis_sizes: int) -> Mesh:
    """Build the framework's canonical 5-axis mesh.

    ``build_mesh(dp=2, tp=4)`` or ``build_mesh(MeshSpec(dp=2, tp=4))``.
    Unspecified axes get size 1 (``dp`` defaults to -1 = remainder), so every
    program is written against the full 5-axis mesh and degrades gracefully to
    fewer chips — the TPU analog of the reference working identically from 1
    to 512 GPUs.
    """
    if spec is None:
        spec = MeshSpec(**axis_sizes)
    elif axis_sizes:
        raise ValueError("Pass either a MeshSpec or keyword sizes, not both.")
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def dp_pp_mesh(dp: int = -1, pp: int = 1,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """The documented two-axis dp x pp mesh for pipelined data-parallel
    training (docs/PERF.md "Pipeline parallelism"): ``dp`` replicas each
    running a ``pp``-deep pipeline. ``dp=-1`` (default) absorbs the
    remaining devices, so ``dp_pp_mesh(pp=4)`` on 8 devices is the
    2x4 layout. ``pp`` is innermost (the canonical axis order), keeping
    stage-to-stage ``ppermute`` traffic on the most tightly coupled
    links while dp gradient reduction can ride slower links. This is
    the mesh constructor behind
    :func:`horovod_tpu.train.pipeline.make_pipeline_train_step` and
    :meth:`horovod_tpu.parallel.plan.ParallelPlan.build_mesh`."""
    return build_mesh(MeshSpec(dp=dp, pp=pp), devices=devices)


def single_axis_mesh(axis: str = DATA_AXIS,
                     devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (axis,))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over every data-like axis present."""
    axes = tuple(a for a in (DATA_AXIS,) if mesh_axis_size(mesh, a) > 1)
    return NamedSharding(mesh, P(axes if axes else None))


# ---------------------------------------------------------------------------
# Logical axis rules (t5x/flax-style): models annotate arrays with logical
# names; the rules map them to mesh axes. This is how one model definition
# serves pure-DP, TP, PP, SP and EP layouts without edits.
# ---------------------------------------------------------------------------

DEFAULT_RULES: Tuple[Tuple[str, Optional[Tuple[str, ...]]], ...] = (
    ("batch", ("dp",)),
    ("seq", ("sp",)),
    ("embed", None),
    ("mlp", ("tp",)),
    ("heads", ("tp",)),
    ("kv", None),
    ("vocab", ("tp",)),
    ("expert", ("ep",)),
    ("stage", ("pp",)),
    ("unsharded", None),
)


class AxisRules:
    def __init__(self, rules: Sequence[Tuple[str, Optional[Sequence[str]]]]
                 = DEFAULT_RULES) -> None:
        self._rules: Dict[str, Optional[Tuple[str, ...]]] = {
            k: (tuple(v) if v is not None else None) for k, v in rules}

    def spec(self, logical_axes: Sequence[Optional[str]], mesh: Mesh) -> P:
        parts: List = []
        used: set = set()
        for name in logical_axes:
            if name is None:
                parts.append(None)
                continue
            mapped = self._rules.get(name)
            if mapped is None:
                parts.append(None)
                continue
            live = tuple(a for a in mapped
                         if mesh_axis_size(mesh, a) > 1 and a not in used)
            used.update(live)
            if not live:
                parts.append(None)
            elif len(live) == 1:
                parts.append(live[0])
            else:
                parts.append(live)
        while parts and parts[-1] is None:
            parts.pop()
        return P(*parts)

    def sharding(self, logical_axes: Sequence[Optional[str]],
                 mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_axes, mesh))


default_rules = AxisRules()


def logical_sharding(mesh: Mesh,
                     logical_axes: Sequence[Optional[str]],
                     rules: Optional[AxisRules] = None) -> NamedSharding:
    return (rules or default_rules).sharding(logical_axes, mesh)
