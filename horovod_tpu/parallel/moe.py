"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

The reference's users build MoE from ``alltoall`` + process sets (SURVEY.md
§2.6: EP "absent as a strategy; alltoall + process sets are the primitives").
Here the full strategy ships: GShard/Switch-style capacity-based dense
dispatch (MXU-friendly einsums, static shapes — no dynamic gather inside
jit) with ``lax.all_to_all`` token exchange across expert shards.

Dataflow per ep-shard (G local tokens, E global experts, C capacity):
  gates = softmax(router(x))                      [G, E]
  dispatch/combine one-hots via top-k + cumsum    [G, E, C]
  xs = einsum(gm,gec->ecm)(x, dispatch)           [E, C, M]
  xs = all_to_all(ep)                             [E/ep, ep*C, M]
  ys = expert_ffn(xs)  (local experts only)
  ys = all_to_all back; y = einsum(ecm,gec->gm)(ys, combine)
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu._compat import axis_size, shard_map


class MoEMetrics(NamedTuple):
    aux_loss: jax.Array       # load-balancing loss (Switch aux loss)
    fraction_dropped: jax.Array


def top_k_gating(logits: jax.Array, k: int, capacity: int
                 ) -> Tuple[jax.Array, jax.Array, MoEMetrics]:
    """Compute dense dispatch/combine tensors.

    logits: [G, E]. Returns dispatch [G, E, C] (0/1), combine [G, E, C]
    (gate weights), metrics.
    """
    G, E = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)          # [G, E]

    # Switch aux loss: E * sum_e (mean_g gates_e * mean_g route_e)
    top1 = jnp.argmax(gates, axis=-1)
    density = jnp.mean(jax.nn.one_hot(top1, E), axis=0)
    density_proxy = jnp.mean(gates, axis=0)
    aux = E * jnp.sum(density * density_proxy)

    dispatch = jnp.zeros((G, E, capacity), jnp.float32)
    combine = jnp.zeros((G, E, capacity), jnp.float32)
    # Track per-expert fill across the k choices so slots are not reused.
    fill = jnp.zeros((E,), jnp.int32)
    masked_gates = gates
    dropped = jnp.zeros((), jnp.float32)
    for _ in range(k):
        choice = jnp.argmax(masked_gates, axis=-1)               # [G]
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.int32)      # [G, E]
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - onehot) \
            + fill[None, :]                                      # [G, E]
        pos = jnp.sum(pos_in_expert * onehot, axis=-1)           # [G]
        keep = pos < capacity
        gate_val = jnp.take_along_axis(
            gates, choice[:, None], axis=-1)[:, 0]               # [G]
        disp = (jax.nn.one_hot(choice, E)[:, :, None]
                * jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1),
                                 capacity)[:, None, :]
                * keep[:, None, None])
        dispatch = dispatch + disp
        combine = combine + disp * gate_val[:, None, None]
        dropped = dropped + jnp.sum(1.0 - keep) / (G * k)
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0)
        masked_gates = masked_gates * (1.0 - jax.nn.one_hot(choice, E))
    return dispatch, combine, MoEMetrics(aux, dropped)


def moe_layer_spmd(x: jax.Array, router_w: jax.Array,
                   expert_fn: Callable[[jax.Array, jax.Array], jax.Array],
                   expert_params, axis_name: str = "ep", k: int = 2,
                   capacity_factor: float = 1.25
                   ) -> Tuple[jax.Array, MoEMetrics]:
    """SPMD MoE (inside shard_map). Local shapes:

    x: [G, M] local tokens; router_w: [M, E] (replicated); expert_params:
    pytree with leading dim E_local = E/ep (this shard's experts).
    expert_fn(params_e, tokens [N, M]) -> [N, M], vmapped over local experts.
    """
    n = axis_size(axis_name) if axis_name else 1
    G, M = x.shape
    E = router_w.shape[1]
    if E % max(n, 1) != 0:
        raise ValueError(f"ep axis size ({n}) must divide n_experts ({E})")
    capacity = max(1, int(capacity_factor * k * G / E))

    logits = x @ router_w                                  # [G, E]
    dispatch, combine, metrics = top_k_gating(logits, k, capacity)

    xs = jnp.einsum("gm,gec->ecm", x.astype(jnp.float32),
                    dispatch).astype(x.dtype)              # [E, C, M]
    if n > 1:
        # split expert dim across shards; gather the source dim into rows:
        # [E, C, M] -> [E/ep, ep*C, M]
        xs = lax.all_to_all(xs, axis_name, split_axis=0, concat_axis=1,
                            tiled=True)
    ys = jax.vmap(expert_fn)(expert_params, xs)            # [E/ep, n*C, M]
    if n > 1:
        ys = lax.all_to_all(ys, axis_name, split_axis=1, concat_axis=0,
                            tiled=True)                    # [E, C, M]
    y = jnp.einsum("ecm,gec->gm", ys.astype(jnp.float32),
                   combine).astype(x.dtype)                # [G, M]
    return y, metrics


def moe_layer(x: jax.Array, router_w: jax.Array, expert_fn: Callable,
              expert_params, mesh: Mesh, axis_name: str = "ep",
              k: int = 2, capacity_factor: float = 1.25,
              token_axes: Tuple[Optional[str], ...] = ("dp",)
              ) -> Tuple[jax.Array, MoEMetrics]:
    """Array-level MoE: x ``[T, M]`` tokens sharded over ``token_axes``;
    expert_params leading dim E sharded over ``axis_name``."""
    from horovod_tpu.parallel.mesh import mesh_axis_size
    n = mesh_axis_size(mesh, axis_name)
    tok_ax = tuple(a for a in token_axes if mesh_axis_size(mesh, a) > 1) \
        or None
    tok_spec = P(tok_ax)
    ep_ax = axis_name if n > 1 else None
    # metrics must be averaged over every axis the computation varies on —
    # the token shards AND the ep shards — to honor the replicated out_spec
    metric_axes = tuple(tok_ax or ()) + ((axis_name,) if n > 1 else ())

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(tok_spec, P(), P(ep_ax)),
        out_specs=(tok_spec, P()), check_vma=False)
    def run(xl, rw, ep_params):
        y, met = moe_layer_spmd(xl, rw, expert_fn, ep_params,
                                axis_name if n > 1 else None,
                                k, capacity_factor)
        if metric_axes:
            met = MoEMetrics(lax.pmean(met.aux_loss, metric_axes),
                             lax.pmean(met.fraction_dropped, metric_axes))
        return y, met

    return run(x, router_w, expert_params)
