"""Ring attention — context parallelism for long sequences over the ``sp``
mesh axis.

No reference analog (SURVEY.md §2.6: sequence/context parallelism is absent
in the reference; ``alltoall`` is its only related primitive). Here it is
first-class: the sequence dim is sharded over ``sp``; K/V blocks rotate
around the ring via ``lax.ppermute`` while every device accumulates its
queries' attention with an online-softmax (flash-style log-sum-exp) update,
so peak memory is O(S/sp) and the ICI transfer overlaps with compute.

Algorithm (Liu et al., Ring Attention; blockwise parallel transformers):
for step t in [0, sp):  partner block = (my_index - t) mod sp
    acc, m, l ← online_softmax_update(acc, m, l, Q_local, K_t, V_t)
    (K_t, V_t) ← ppermute ring shift
Causal masking uses absolute block offsets so the result is bit-equivalent
to full attention with a causal mask.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu._compat import axis_size, shard_map

NEG_INF = -1e30


def _block_attend(q, k, v, bias, scale):
    """One blockwise attention contribution with running-softmax stats.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; bias: [B, H, Sq, Sk] or None.
    Returns (scores_max [B,H,Sq], exp_scores [B,H,Sq,Sk], weighted_v
    [B,Sq,H,D] un-normalized).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1)                      # [B,H,Sq]
    p = jnp.exp(s - m[..., None])                # [B,H,Sq,Sk]
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)     # [B,Sq,H,D]
    l = jnp.sum(p, axis=-1)                      # [B,H,Sq]
    return m, l, pv


def _online_update(acc, m_run, l_run, m_new, l_new, pv_new):
    """Flash-attention accumulator merge of two partial softmaxes."""
    m_next = jnp.maximum(m_run, m_new)
    a = jnp.exp(m_run - m_next)                  # rescale old
    b = jnp.exp(m_new - m_next)                  # rescale new
    l_next = l_run * a + l_new * b
    # acc: [B,Sq,H,D]; a/b: [B,H,Sq] → [B,Sq,H,1]
    a_ = jnp.transpose(a, (0, 2, 1))[..., None]
    b_ = jnp.transpose(b, (0, 2, 1))[..., None]
    acc_next = acc * a_ + pv_new * b_
    return acc_next, m_next, l_next


def ring_attention_spmd(q: jax.Array, k: jax.Array, v: jax.Array,
                        axis_name: str = "sp", causal: bool = True,
                        scale: Optional[float] = None,
                        use_flash: Optional[bool] = None,
                        interpret: bool = False) -> jax.Array:
    """SPMD body: call inside ``shard_map`` with sequence sharded on
    ``axis_name``. Shapes (local): q/k/v ``[B, S_local, H, D]``.

    The K/V pair travels the ring; accumulation order is fixed by absolute
    block index so causal masking stays exact.

    ``use_flash`` selects the Pallas flash kernel for each ring step's
    local block attention (auto: on TPU when tiling permits): every step
    returns a normalized ``(o, lse)`` partial which merges exactly via
    logaddexp, so the O(Sq·Sk_local) score matrix is never materialized.
    Ring causal masking needs no in-kernel offsets — a step's K/V block
    is fully visible (earlier block), diagonal (own block: standard
    causal), or fully masked (later block: skipped).
    """
    B, Sq, H, D = q.shape
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale = scale if scale is not None else (1.0 / (D ** 0.5))

    if use_flash is None:
        from horovod_tpu.ops.pallas_attention import BLOCK_K, BLOCK_Q
        use_flash = (jax.default_backend() == "tpu" and D % 128 == 0
                     and Sq % BLOCK_Q == 0 and k.shape[1] % BLOCK_K == 0)
    if use_flash:
        return _ring_flash(q, k, v, axis_name, causal, scale, n, my,
                           interpret)

    acc = jnp.zeros((B, Sq, H, D), jnp.float32)
    m_run = jnp.full((B, H, Sq), NEG_INF, jnp.float32)
    l_run = jnp.zeros((B, H, Sq), jnp.float32)

    qf = q.astype(jnp.float32)

    def attend(t, acc, m_run, l_run, k_t, v_t):
        src_block = (my - t) % n                  # whose K/V we hold now
        if causal:
            # absolute positions: q row i ↔ my*Sq+i; k col j ↔ src*Sk+j
            qpos = my * Sq + jnp.arange(Sq)
            kpos = src_block * k_t.shape[1] + jnp.arange(k_t.shape[1])
            mask = qpos[:, None] >= kpos[None, :]
            bias = jnp.where(mask, 0.0, NEG_INF)[None, None]
        else:
            bias = None
        m_new, l_new, pv = _block_attend(qf, k_t.astype(jnp.float32),
                                         v_t.astype(jnp.float32), bias, scale)
        return _online_update(acc, m_run, l_run, m_new, l_new, pv)

    def body(t, carry):
        acc, m_run, l_run, k_t, v_t = carry
        acc, m_run, l_run = attend(t, acc, m_run, l_run, k_t, v_t)
        # rotate K/V to the next device (ring); overlapped with next block's
        # compute by XLA's async collective scheduling on TPU
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_t = lax.ppermute(k_t, axis_name, perm)
        v_t = lax.ppermute(v_t, axis_name, perm)
        return acc, m_run, l_run, k_t, v_t

    # n-1 rotate-and-attend steps, then the final block without the wasted
    # last rotation (its result would be discarded)
    acc, m_run, l_run, k_t, v_t = lax.fori_loop(
        0, n - 1, body, (acc, m_run, l_run, k, v))
    acc, m_run, l_run = attend(n - 1, acc, m_run, l_run, k_t, v_t)
    # normalize: acc / l  (l: [B,H,Sq] → [B,Sq,H,1]); guard fully-masked rows
    l_ = jnp.transpose(l_run, (0, 2, 1))[..., None]
    out = acc / jnp.maximum(l_, 1e-30)
    return out.astype(q.dtype)


def _ring_flash(q, k, v, axis_name, causal, scale, n, my, interpret):
    """Flash-kernel ring body: per step, the local block attention runs in
    the Pallas kernel and the normalized ``(o, lse)`` partials merge via
    logaddexp (``o_tot = Σ o_i · exp(lse_i − lse_tot)``)."""
    from horovod_tpu.ops.pallas_attention import flash_attention_with_lse

    B, Sq, H, D = q.shape

    def attend_step(t, acc, lse_run, k_t, v_t):
        def full(kv):
            return flash_attention_with_lse(q, kv[0], kv[1], causal=False,
                                            scale=scale, interpret=interpret)

        def diag(kv):
            return flash_attention_with_lse(q, kv[0], kv[1], causal=True,
                                            scale=scale, interpret=interpret)

        def skip(kv):
            return (jnp.zeros((B, Sq, H, D), q.dtype),
                    jnp.full((B * H, Sq), NEG_INF, jnp.float32))

        if causal:
            src = (my - t) % n                    # whose K/V we hold now
            idx = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            o_t, lse_t = lax.switch(idx, [full, diag, skip], (k_t, v_t))
        else:
            o_t, lse_t = full((k_t, v_t))

        lse_new = jnp.logaddexp(lse_run, lse_t)   # [BH, Sq]
        # weights: [BH,Sq] → [B,Sq,H,1] (finite NEG_INF keeps this NaN-free)
        def w(x):
            return jnp.exp(x - lse_new).reshape(B, H, Sq).transpose(
                0, 2, 1)[..., None]
        acc = acc * w(lse_run) + o_t.astype(jnp.float32) * w(lse_t)
        return acc, lse_new

    acc = jnp.zeros((B, Sq, H, D), jnp.float32)
    lse_run = jnp.full((B * H, Sq), NEG_INF, jnp.float32)

    def body(t, carry):
        acc, lse_run, k_t, v_t = carry
        acc, lse_run = attend_step(t, acc, lse_run, k_t, v_t)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_t = lax.ppermute(k_t, axis_name, perm)
        v_t = lax.ppermute(v_t, axis_name, perm)
        return acc, lse_run, k_t, v_t

    acc, lse_run, k_t, v_t = lax.fori_loop(
        0, n - 1, body, (acc, lse_run, k, v))
    acc, _ = attend_step(n - 1, acc, lse_run, k_t, v_t)
    return acc.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None,
                   batch_axis: Optional[str] = "dp",
                   use_flash: Optional[bool] = None,
                   interpret: bool = False) -> jax.Array:
    """Array-level ring attention: global ``[B, S, H, D]`` inputs with S
    sharded over ``axis_name`` (and optionally B over ``batch_axis``)."""
    from horovod_tpu.parallel.mesh import mesh_axis_size
    if mesh_axis_size(mesh, axis_name) == 1:
        # degenerate ring: plain attention
        return _plain_attention(q, k, v, causal, scale)
    b_ax = batch_axis if (batch_axis and mesh_axis_size(mesh, batch_axis) > 1) \
        else None
    spec = P(b_ax, axis_name)

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec,) * 3,
                       out_specs=spec, check_vma=False)
    def run(ql, kl, vl):
        return ring_attention_spmd(ql, kl, vl, axis_name, causal, scale,
                                   use_flash=use_flash, interpret=interpret)

    return run(q, k, v)


def _plain_attention(q, k, v, causal=True, scale=None):
    """Single-device reference attention (the correctness oracle for the
    ring; also the sp=1 fast path)."""
    B, S, H, D = q.shape
    scale = scale if scale is not None else (1.0 / (D ** 0.5))
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, k.shape[1]), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
