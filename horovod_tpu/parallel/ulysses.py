"""Ulysses-style sequence parallelism: all_to_all head↔sequence re-sharding.

The reference exposes the ``alltoall`` primitive this is built on
(``operations.cc:1630-1710``) but not the strategy (SURVEY.md §2.6). Here the
full pattern is provided: sequence-sharded activations are re-sharded to
head-sharded for exact (non-blocked) attention, then re-sharded back —
2 all_to_alls per attention instead of a ring of ppermutes. On TPU both
all_to_alls ride ICI; Ulysses is preferable when H >= sp and sequence blocks
are small; ring attention when S is huge (memory-bound).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu._compat import axis_size, shard_map

from horovod_tpu.parallel.ring_attention import _plain_attention


def ulysses_attention_spmd(q: jax.Array, k: jax.Array, v: jax.Array,
                           axis_name: str = "sp", causal: bool = True,
                           scale: Optional[float] = None) -> jax.Array:
    """SPMD body (inside shard_map): local shapes ``[B, S/sp, H, D]``.

    all_to_all #1: scatter heads, gather sequence → ``[B, S, H/sp, D]``;
    exact attention on full sequence for the local head group;
    all_to_all #2: scatter sequence, gather heads → ``[B, S/sp, H, D]``.
    """
    n = axis_size(axis_name)
    B, Sl, H, D = q.shape
    if H % n != 0:
        raise ValueError(f"Ulysses needs heads ({H}) divisible by axis ({n})")
    # [B, S/sp, H, D] -> split heads -> gather seq: [B, S, H/sp, D]
    def to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)
    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)
    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    from horovod_tpu.ops.pallas_attention import attend
    out = attend(qh, kh, vh, causal, scale)
    return to_seq(out)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                      axis_name: str = "sp", causal: bool = True,
                      scale: Optional[float] = None,
                      batch_axis: Optional[str] = "dp") -> jax.Array:
    """Array-level wrapper: global ``[B, S, H, D]``, S sharded on axis."""
    from horovod_tpu.parallel.mesh import mesh_axis_size
    if mesh_axis_size(mesh, axis_name) == 1:
        return _plain_attention(q, k, v, causal, scale)
    b_ax = batch_axis if (batch_axis and mesh_axis_size(mesh, batch_axis) > 1) \
        else None
    spec = P(b_ax, axis_name)

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec,) * 3,
                       out_specs=spec, check_vma=False)
    def run(ql, kl, vl):
        return ulysses_attention_spmd(ql, kl, vl, axis_name, causal, scale)

    return run(q, k, v)
