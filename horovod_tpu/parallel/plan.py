"""Unified parallelism plan: "how should this model run on this mesh"
as a frozen, cacheable object (ROADMAP item 1).

PR 8 froze the COMMUNICATION decision into
:class:`horovod_tpu.train.autotune.Plan` (bucket bytes x algorithm x
codec x small floor) and made it a searched, fingerprint-cached choice.
This module generalizes that object one level up: a
:class:`ParallelPlan` fixes the dp x pp mesh split, the pipeline
schedule (GPipe / 1F1B / interleaved-1F1B with ``virtual_stages``
chunks per device), the microbatch count, and NESTS a communication
plan for the dp gradient traffic. The same successive-halving search
(``train/autotune.py``) scores whole parallelism plans by measured step
time and persists the winner to the same plan cache, so an elastic
re-mesh back to a seen world locks dp split, schedule, microbatching
AND communication config with zero trials.

:func:`compile_step_with_plan` is the Titanax-style single compile seam
(SNIPPETS.md [2]/[3]): ``pjit`` (jit with explicit shardings) when the
caller provides shardings, ``shard_map`` for map-style SPMD bodies, and
a plain mesh-scoped ``jit`` on a single device. Step factories go
through this one entry point so "how a step is compiled" is decided by
the plan, not scattered per call site.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

SCHEDULES: Tuple[str, ...] = ("gpipe", "1f1b", "interleaved")


def _comm_plan_cls():
    # lazy: parallel.plan must stay importable without pulling the train
    # package's heavier deps at import time
    from horovod_tpu.train.autotune import Plan
    return Plan


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    """One point in the parallelism search space.

    ``dp`` x ``pp`` must multiply to the device count the plan is bound
    to. ``schedule``: ``gpipe`` (all forwards, then autodiff backward —
    fastest ticks, activation memory grows with ``n_microbatches``),
    ``1f1b`` (combined fwd+bwd ticks, ``min(2*pp-1, M)``-entry remat
    ring — bounded memory), ``interleaved`` (1F1B with
    ``virtual_stages`` chunks per device — ``~1/v`` of the 1F1B fill/
    drain bubble at the same ``M``). ``comms`` is the nested
    communication :class:`~horovod_tpu.train.autotune.Plan` for dp
    gradient reduction (None = dense psum defaults).
    """

    dp: int = 1
    pp: int = 1
    schedule: str = "1f1b"
    n_microbatches: int = 1
    virtual_stages: int = 1
    comms: Optional[Any] = None

    def __post_init__(self):
        if self.dp < 1 or self.pp < 1:
            raise ValueError(
                f"dp and pp must be >= 1, got dp={self.dp} pp={self.pp}")
        if self.schedule not in SCHEDULES:
            raise ValueError(f"unknown schedule {self.schedule!r}; "
                             f"expected one of {SCHEDULES}")
        if self.n_microbatches < 1:
            raise ValueError("n_microbatches must be >= 1")
        if self.virtual_stages < 1:
            raise ValueError("virtual_stages must be >= 1")
        if self.virtual_stages > 1 and self.schedule != "interleaved":
            raise ValueError(
                f"virtual_stages={self.virtual_stages} only makes sense "
                f"for the interleaved schedule, not {self.schedule!r}")
        if self.pp > 1 and self.n_microbatches < 2:
            raise ValueError(
                "a pipeline (pp > 1) needs n_microbatches >= 2 — with one "
                "microbatch every schedule is pure bubble")
        if self.comms is not None and not hasattr(self.comms, "step_kwargs"):
            raise ValueError(
                f"comms must be a communication Plan (train.autotune.Plan), "
                f"got {self.comms!r}")

    # -- identity -----------------------------------------------------------

    @property
    def world(self) -> int:
        return self.dp * self.pp

    @property
    def total_stages(self) -> int:
        return self.pp * self.virtual_stages

    @property
    def key(self) -> str:
        """Short human label (CSV / flight / metric labels)."""
        base = f"dp{self.dp}xpp{self.pp}/{self.schedule}"
        if self.schedule == "interleaved":
            base += f"v{self.virtual_stages}"
        base += f"/m{self.n_microbatches}"
        if self.comms is not None:
            base += f"[{self.comms.key}]"
        return base

    # the communication-plan facade: the shared autotune controller /
    # CSV trace / locked-plan gauges read these four knobs off any plan
    # they score, so a ParallelPlan delegates to its nested comms plan
    @property
    def bucket_bytes(self) -> int:
        return self.comms.bucket_bytes if self.comms is not None else 0

    @property
    def algorithm(self) -> str:
        return self.comms.algorithm if self.comms is not None else "psum"

    @property
    def codec(self) -> str:
        return self.comms.codec if self.comms is not None else "none"

    @property
    def small_floor(self) -> int:
        return self.comms.small_floor if self.comms is not None else 0

    # -- serialization (plan cache) -----------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        d = {"kind": "parallel", "dp": self.dp, "pp": self.pp,
             "schedule": self.schedule,
             "n_microbatches": self.n_microbatches,
             "virtual_stages": self.virtual_stages}
        if self.comms is not None:
            d["comms"] = self.comms.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ParallelPlan":
        comms = d.get("comms")
        return cls(dp=int(d["dp"]), pp=int(d["pp"]),
                   schedule=str(d.get("schedule", "1f1b")),
                   n_microbatches=int(d.get("n_microbatches", 1)),
                   virtual_stages=int(d.get("virtual_stages", 1)),
                   comms=_comm_plan_cls().from_dict(comms)
                   if comms is not None else None)

    # -- analytics / binding ------------------------------------------------

    def bubble_fraction(self) -> float:
        """Analytic fill+drain bubble fraction of this plan's schedule
        (0.0 when pp == 1; docs/PERF.md "Pipeline parallelism")."""
        from horovod_tpu.parallel.pipeline import bubble_fraction
        return bubble_fraction(self.schedule, self.pp,
                               self.n_microbatches, self.virtual_stages)

    def build_mesh(self, devices: Optional[Sequence] = None):
        """Realize this plan's dp x pp mesh
        (:func:`horovod_tpu.parallel.mesh.dp_pp_mesh`)."""
        from horovod_tpu.parallel.mesh import dp_pp_mesh
        return dp_pp_mesh(dp=self.dp, pp=self.pp, devices=devices)

    def validate_for(self, n_devices: int, n_layers: Optional[int] = None,
                     batch_per_replica: Optional[int] = None) -> None:
        """Bind-time checks: the plan must tile ``n_devices`` exactly;
        ``n_layers`` (when known) must split into ``total_stages`` equal
        chunks; the per-replica batch must split into microbatches."""
        if self.world != n_devices:
            raise ValueError(
                f"plan {self.key} needs dp*pp == {self.world} devices, "
                f"have {n_devices}")
        if n_layers is not None and n_layers % self.total_stages != 0:
            raise ValueError(
                f"{n_layers} layers not divisible into "
                f"{self.total_stages} stages (pp={self.pp} x "
                f"v={self.virtual_stages})")
        if batch_per_replica is not None \
                and batch_per_replica % self.n_microbatches != 0:
            raise ValueError(
                f"per-replica batch {batch_per_replica} not divisible by "
                f"{self.n_microbatches} microbatches")


def plan_from_dict(d: Dict[str, Any]):
    """Revive a plan of either kind from its cache dict: a
    :class:`ParallelPlan` when the doc says so (``kind`` tag or pipeline
    fields), else a communication
    :class:`~horovod_tpu.train.autotune.Plan`."""
    if d.get("kind") == "parallel" or "schedule" in d:
        return ParallelPlan.from_dict(d)
    return _comm_plan_cls().from_dict(d)


# ---------------------------------------------------------------------------
# The single compile seam (Titanax-style, SNIPPETS.md [2]/[3])
# ---------------------------------------------------------------------------

def compile_step_with_plan(step_fn: Callable, mesh, *,
                           in_shardings=None, out_shardings=None,
                           in_specs=None, out_specs=None,
                           donate_argnums: Tuple[int, ...] = (),
                           static_argnums: Tuple[int, ...] = (),
                           check_vma: bool = False) -> Callable:
    """Compile a step function one of three ways, chosen by what the
    caller can describe:

    * **pjit path** — explicit ``in_shardings``/``out_shardings``
      (BOTH required): ``jax.jit`` with shardings. For GSPMD-auto
      programs where the sharding annotations carry the parallelism.
    * **shard_map path** — ``in_specs``/``out_specs`` (BOTH required):
      map-style SPMD body (collectives spelled out: psum/ppermute/...)
      wrapped in ``shard_map`` then jitted. This is what every pure-DP
      and pipeline step factory uses.
    * **single-device / fallback** — neither given, or the mesh has one
      device: plain ``jax.jit`` with the mesh entered around the body,
      so ``lax.axis_index``-free code runs unchanged.

    Mixing the two description styles, or providing only half of one,
    raises — the seam exists so there is exactly one way a step gets
    compiled for a given plan.
    """
    import jax

    from horovod_tpu._compat import shard_map

    have_shardings = (in_shardings is not None) or (out_shardings is not None)
    have_specs = (in_specs is not None) or (out_specs is not None)
    if have_shardings and have_specs:
        raise ValueError(
            "pass either explicit shardings (pjit path) or shard_map "
            "specs, not both")
    if have_shardings and (in_shardings is None or out_shardings is None):
        raise ValueError(
            "compile_step_with_plan requires BOTH in_shardings and "
            "out_shardings for the pjit path")
    if have_specs and (in_specs is None or out_specs is None):
        raise ValueError(
            "compile_step_with_plan requires BOTH in_specs and out_specs "
            "for the shard_map path")

    if have_shardings:
        return jax.jit(step_fn, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=donate_argnums,
                       static_argnums=static_argnums)
    if have_specs:
        # even on a 1-device mesh: the body may use named-axis
        # collectives (axis size 1), which only exist under shard_map
        mapped = shard_map(step_fn, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=check_vma)
        return jax.jit(mapped, donate_argnums=donate_argnums,
                       static_argnums=static_argnums)

    def single_device_fn(*args, **kwargs):
        if mesh is not None:
            with mesh:
                return step_fn(*args, **kwargs)
        return step_fn(*args, **kwargs)

    return jax.jit(single_device_fn, donate_argnums=donate_argnums,
                   static_argnums=static_argnums)
