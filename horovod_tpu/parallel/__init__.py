from horovod_tpu.parallel.mesh import (  # noqa: F401
    AXIS_ORDER,
    AxisRules,
    DATA_AXIS,
    EXPERT_AXIS,
    MeshSpec,
    PIPELINE_AXIS,
    SEQUENCE_AXIS,
    TENSOR_AXIS,
    batch_sharding,
    build_mesh,
    default_rules,
    dp_pp_mesh,
    logical_sharding,
    mesh_axis_size,
    replicated,
    sharded,
    single_axis_mesh,
)
from horovod_tpu.parallel.plan import (  # noqa: F401
    ParallelPlan,
    SCHEDULES,
    compile_step_with_plan,
    plan_from_dict,
)
from horovod_tpu.parallel.pipeline import (  # noqa: F401
    bubble_fraction,
    pipeline_1f1b_apply,
    pipeline_apply,
    pipeline_interleaved_apply,
    schedule_ticks,
    stage_stacked,
)
