"""Tree-aggregated fleet metrics: one scrape for the whole job.

Per-worker ``/metrics`` endpoints (PR 1) scale the *serving* side, but a
whole-job view still meant scraping W workers — O(world) work for the
consumer, and exactly the pattern ROADMAP item 5 forbids at 1000+
ranks.  This module turns the workers into a **fan-in tree**: every
rank periodically pushes its mergeable registry snapshot (merged with
whatever its children last pushed) to its parent over the existing
exporter HTTP plane (``POST /metrics/push``), so data flows rank →
parent → ... → rank 0, each node handling at most ``arity`` children
and one upstream push per interval — O(arity) per node, O(log_arity W)
hops end to end.  Rank 0 serves the merged result on
``GET /metrics/fleet`` with per-rank breakdown gauges (min/max/mean
windowed step time, the currently-charged straggler rank, how many
ranks are reporting), so a dashboard scrapes ONE endpoint regardless of
world size.

Topology: parent(r) = (r-1) // arity; children(r) = r*arity+1 ...
r*arity+arity (a complete ``arity``-ary tree over ranks — computed
locally from (rank, size), no negotiation).  Addressing reuses the
exporter contract (base port + local rank; ``HVD_TPU_PEER_HOSTS`` for
multi-host, exactly like the autopsy's peer fetch).

Elastic: the aggregator is built by ``hvd.init`` and torn down by
``hvd.shutdown``, so a re-mesh re-wires the tree from the new (rank,
size) automatically; pushed documents carry the sender's (size,
generation) and a receiver rejects documents from a different world —
a straggling push from the pre-re-mesh generation cannot pollute the
new tree.  A dead parent degrades gracefully: the child keeps its
subtree and retries every interval (logged once per outage, not per
tick), and rank 0's ``ranks_reporting`` gauge makes the gap visible;
entries older than ``3 × push interval`` go stale and drop out of the
merge rather than serving dead data.

Knobs (docs/KNOBS.md): ``HVD_TPU_FLEET_PUSH_SECONDS`` (default 2),
``HVD_TPU_FLEET_ARITY`` (default 4), ``HVD_TPU_FLEET=0`` disables.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from horovod_tpu.common.logging import get_logger
from horovod_tpu.metrics.registry import (Registry, default_registry,
                                          render_prometheus)

DEFAULT_PUSH_SECONDS = 2.0
DEFAULT_ARITY = 4
_PUSH_TIMEOUT_S = 5.0


def push_interval_s() -> float:
    from horovod_tpu.common.config import env_float
    return max(0.05, env_float("FLEET_PUSH_SECONDS", DEFAULT_PUSH_SECONDS))


def tree_arity() -> int:
    from horovod_tpu.common.config import env_int
    return max(1, env_int("FLEET_ARITY", DEFAULT_ARITY))


def fleet_enabled() -> bool:
    from horovod_tpu.common.config import env_bool
    return env_bool("FLEET", True)


def parent_of(rank: int, arity: int) -> Optional[int]:
    return None if rank <= 0 else (rank - 1) // arity


def children_of(rank: int, size: int, arity: int) -> List[int]:
    first = rank * arity + 1
    return [c for c in range(first, min(first + arity, size))]


def tree_depth(size: int, arity: int) -> int:
    """Hops from the deepest rank to rank 0 (0 for a 1-rank world)."""
    d, r = 0, size - 1
    while r > 0:
        r = (r - 1) // arity
        d += 1
    return d


def rank_endpoint(rank: int, base_port: int) -> Tuple[str, int]:
    """(host, exporter port) for ``rank`` — the SAME helper the autopsy
    peer fetch uses (:func:`horovod_tpu.metrics.exporter.peer_endpoint`),
    fed from ``HVD_TPU_PEER_HOSTS``; one implementation of the
    exporter addressing contract, not a fork of it."""
    from horovod_tpu.metrics.exporter import peer_endpoint
    hosts_env = os.environ.get("HVD_TPU_PEER_HOSTS", "")
    hosts = [h.strip() for h in hosts_env.split(",")] if hosts_env else None
    return peer_endpoint(rank, base_port, hosts)


class FleetAggregator:
    """One node of the fan-in tree.

    Args:
      rank/size: this worker's identity in the current world.
      base_port: exporter base port (push target = parent's exporter).
      registry: local registry contributing this rank's snapshot.
      collectors: refreshed before each local snapshot (same callables
        the exporter runs at scrape time, so pushed data is as fresh as
        scraped data).
      generation: world generation stamped into pushed docs (elastic
        re-mesh bumps it; mismatched docs are rejected).
      push_interval/arity: override the env knobs (tests).
    """

    def __init__(self, rank: int, size: int, base_port: int,
                 registry: Optional[Registry] = None,
                 collectors: Optional[List[Callable[[], None]]] = None,
                 generation: int = 0,
                 push_interval: Optional[float] = None,
                 arity: Optional[int] = None,
                 cross_size: int = 1) -> None:
        self.rank = int(rank)
        self.size = int(size)
        self.base_port = int(base_port)
        self.generation = int(generation)
        self.arity = arity or tree_arity()
        self.interval = push_interval or push_interval_s()
        self.stale_after = 3.0 * self.interval
        self._reg = registry or default_registry()
        self._collectors = list(collectors or [])
        self.parent = parent_of(self.rank, self.arity)
        self.children = children_of(self.rank, self.size, self.arity)
        # multi-host without a rank->host map: upstream addresses
        # cannot be derived — refuse to guess loopback (the autopsy
        # peer map makes the same call); local aggregation + the
        # subtree endpoint keep working, only the upstream push is off
        self.routable = self.parent is None or cross_size <= 1 \
            or bool(os.environ.get("HVD_TPU_PEER_HOSTS", ""))
        if not self.routable:
            get_logger().warning(
                "fleet: multi-host layout (cross_size=%d) without "
                "HVD_TPU_PEER_HOSTS — upstream pushes disabled for "
                "rank %d (set the rank->host map to enable the tree)",
                cross_size, self.rank)
        self._lock = threading.Lock()
        # child rank -> (doc, monotonic arrival time)
        self._child_docs: Dict[int, Tuple[dict, float]] = {}
        # windowed per-rank step time: previous (sum, count) of the
        # local step-time histogram, delta'd per PUSH (scrapes read the
        # window without consuming it), + the last closed window's mean
        # so an idle rank stays in the breakdown instead of vanishing
        self._prev_hist: Optional[Tuple[float, int]] = None
        self._last_win: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._push_failures = 0
        self.pushes_sent = 0
        self.pushes_received = 0
        self.rejected = 0

    # -- local contribution --------------------------------------------------
    def _local_snapshot(self) -> dict:
        for fn in self._collectors:
            try:
                fn()
            except Exception as e:
                get_logger().debug("fleet collector %r failed: %r", fn, e)
        return self._reg.snapshot()

    def _local_per_rank(self, snap: dict, consume: bool) -> dict:
        """This rank's breakdown entry: cumulative steps plus the step
        time averaged over THIS push window (delta of the histogram's
        sum/count since the previous push) — 'recent', not
        since-forever, so a developing straggler shows immediately.

        ``consume=False`` (scrapes) reads the in-progress window
        WITHOUT closing it: a dashboard polling /metrics/fleet faster
        than the push cadence must not starve the data the next
        upstream push (and the straggler detector) reports.  A window
        with no new steps carries the last closed window's mean — an
        idle-but-alive rank stays in the min/max/mean breakdown."""
        entry: Dict[str, object] = {"ts": round(time.time(), 3)}
        h = snap.get("hvd_step_time_seconds")
        if h and h.get("type") == "histogram":
            s, c = float(h["sum"]), int(h["count"])
            entry["steps"] = c
            if c > 0:
                entry["mean_step_time"] = round(s / c, 6)
            with self._lock:
                # first push: the window is everything so far — a
                # straggler shows from the tree's very first aggregation
                prev = self._prev_hist or (0.0, 0)
                if c > prev[1]:
                    win = round((s - prev[0]) / (c - prev[1]), 6)
                else:
                    win = self._last_win
                if consume:
                    self._prev_hist = (s, c)
                    self._last_win = win
            if win is not None:
                entry["win_step_time"] = win
        try:
            # goodput ledger (docs/OBSERVABILITY.md "Goodput ledger"):
            # last closed window's productive fraction + dominating
            # loss category ride the breakdown entry, so rank 0 can
            # name the fleet's worst offender without extra traffic
            from horovod_tpu.metrics import goodput
            gp = goodput.fleet_summary()
            if gp is not None:
                entry["goodput"] = gp
        except Exception:
            pass
        return entry

    # -- tree plumbing -------------------------------------------------------
    def ingest(self, doc: dict) -> bool:
        """A child's pushed subtree document (exporter ``/metrics/push``
        handler calls this).  Returns False (and counts a rejection)
        for documents from another world or an unknown child."""
        try:
            child = int(doc["from_rank"])
        except (KeyError, TypeError, ValueError):
            self.rejected += 1
            return False
        if int(doc.get("size", -1)) != self.size or \
                int(doc.get("generation", -1)) != self.generation or \
                child not in self.children:
            self.rejected += 1
            get_logger().debug(
                "fleet: rejected push from rank %s (size %s gen %s; "
                "we are size %d gen %d, children %s)", child,
                doc.get("size"), doc.get("generation"), self.size,
                self.generation, self.children)
            return False
        with self._lock:
            self._child_docs[child] = (doc, time.monotonic())
            self.pushes_received += 1
        return True

    def subtree_doc(self, consume_window: bool = True) -> dict:
        """Merge this rank's snapshot with every FRESH child subtree —
        the document pushed upstream, and what ``/metrics/fleet``
        renders on rank 0 (scrapes pass ``consume_window=False`` so
        they observe without advancing the push window)."""
        snap = self._local_snapshot()
        per_rank = {str(self.rank): self._local_per_rank(
            snap, consume=consume_window)}
        covers = [self.rank]
        snaps = [snap]
        now = time.monotonic()
        with self._lock:
            items = list(self._child_docs.items())
        stale = []
        for child, (doc, ts) in items:
            if now - ts > self.stale_after:
                stale.append(child)
                continue
            snaps.append(doc.get("snapshot") or {})
            per_rank.update(doc.get("per_rank") or {})
            covers.extend(doc.get("covers") or [])
        try:
            merged = Registry.merge(snaps)
        except ValueError as e:
            # a mid-rollout worker with different histogram bounds must
            # not take the whole fleet view down — serve local + note it
            get_logger().warning("fleet: snapshot merge failed (%r); "
                                 "serving local-only view", e)
            merged = snap
            covers = [self.rank]
            per_rank = {str(self.rank): per_rank[str(self.rank)]}
        return {"from_rank": self.rank, "size": self.size,
                "generation": self.generation,
                "covers": sorted(set(covers)), "stale": sorted(stale),
                "per_rank": per_rank, "snapshot": merged,
                "ts": round(time.time(), 3)}

    def _push_upstream(self, doc: dict) -> None:
        host, port = rank_endpoint(self.parent, self.base_port)
        url = f"http://{host}:{port}/metrics/push"
        body = json.dumps(doc, default=str).encode()
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=_PUSH_TIMEOUT_S).read()
        except Exception as e:
            self._push_failures += 1
            if self._push_failures in (1, 10) or \
                    self._push_failures % 100 == 0:
                # once per outage start (and sparsely after), not per
                # tick — a dead parent at a 2s cadence must not flood
                get_logger().warning(
                    "fleet: push to parent rank %s (%s) failed %d time(s)"
                    ": %r", self.parent, url, self._push_failures, e)
            return
        if self._push_failures:
            get_logger().info("fleet: push to parent rank %s recovered "
                              "after %d failure(s)", self.parent,
                              self._push_failures)
        self._push_failures = 0
        self.pushes_sent += 1

    # -- rank-0 view ---------------------------------------------------------
    def fleet_snapshot(self) -> dict:
        """The merged fleet snapshot plus derived breakdown gauges —
        what ``/metrics/fleet`` renders.  Read-only with respect to the
        push window: scraping must never change what gets pushed."""
        doc = self.subtree_doc(consume_window=False)
        merged = dict(doc["snapshot"])
        covers = doc["covers"]

        def g(name, value, help, labels=None, agg="last"):
            key = name
            if labels:
                items = sorted(labels.items())
                key += "{" + ",".join(f'{k}="{v}"' for k, v in items) + "}"
            merged[key] = {"type": "gauge", "help": help, "agg": agg,
                           "value": float(value)}

        g("hvd_fleet_size", self.size, "world size of the fleet view")
        g("hvd_fleet_ranks_reporting", len(covers),
          "ranks contributing fresh samples to this fleet view")
        g("hvd_fleet_tree_depth",
          tree_depth(self.size, self.arity),
          "fan-in tree depth (hops from deepest rank to rank 0)")
        g("hvd_fleet_generation", self.generation,
          "world generation this tree was wired for")
        win = {int(r): e["win_step_time"]
               for r, e in doc["per_rank"].items()
               if isinstance(e, dict)
               and isinstance(e.get("win_step_time"), (int, float))}
        for r, e in sorted(doc["per_rank"].items(), key=lambda kv: kv[0]):
            if isinstance(e, dict) and "win_step_time" in e:
                g("hvd_fleet_rank_step_time_seconds", e["win_step_time"],
                  "windowed mean step time of this rank",
                  labels={"rank": str(r)})
        if win:
            vals = list(win.values())
            g("hvd_fleet_step_time_min", min(vals),
              "fastest rank's windowed mean step time")
            g("hvd_fleet_step_time_max", max(vals),
              "slowest rank's windowed mean step time")
            g("hvd_fleet_step_time_mean", sum(vals) / len(vals),
              "fleet mean windowed step time")
            g("hvd_fleet_straggler_rank", max(win, key=lambda r: win[r]),
              "rank with the slowest windowed mean step time")
        gp = {int(r): e["goodput"]["fraction"]
              for r, e in doc["per_rank"].items()
              if isinstance(e, dict) and isinstance(e.get("goodput"), dict)
              and isinstance(e["goodput"].get("fraction"), (int, float))}
        for r in sorted(gp):
            g("hvd_fleet_rank_goodput_fraction", gp[r],
              "last goodput window's productive fraction of this rank",
              labels={"rank": str(r)})
        if gp:
            worst = min(gp, key=lambda r: gp[r])
            g("hvd_fleet_goodput_min", gp[worst],
              "worst rank's productive goodput fraction")
            g("hvd_fleet_goodput_worst_rank", worst,
              "rank with the lowest productive goodput fraction")
        return {"doc": doc, "snapshot": merged}

    def render_fleet(self) -> str:
        return render_prometheus(self.fleet_snapshot()["snapshot"])

    # -- lifecycle -----------------------------------------------------------
    def _tick(self) -> None:
        doc = self.subtree_doc()
        if self.parent is not None:
            if not self.routable:
                return  # multi-host without PEER_HOSTS: warned at init
            self._push_upstream(doc)
        else:
            # rank 0: feed the persistent-straggler detector and record
            # a fleet point into the time-series history
            try:
                from horovod_tpu.metrics import anomaly
                eng = anomaly.default_engine()
                if eng is not None:
                    eng.observe_fleet(doc["per_rank"])
            except Exception:
                pass

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._tick()
            except Exception as e:  # the tree must outlive a bad tick
                get_logger().debug("fleet tick failed: %r", e)

    def start(self) -> "FleetAggregator":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="hvd-tpu-fleet", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def flush(self) -> None:
        """Push/aggregate NOW (tests and pre-scrape freshness)."""
        self._tick()
