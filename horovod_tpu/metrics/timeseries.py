"""Step-aligned time-series history: a bounded ring + append-only JSONL.

The registry (:mod:`horovod_tpu.metrics.registry`) answers *what is the
value now*; nothing in the stack remembered *how it got there* — a
regression noticed at step 10k could not say whether it arrived as a
cliff or a drift.  This module is the history layer: every completed
step lands as a small point in a bounded in-memory ring (always on,
drop-oldest, same philosophy as the flight recorder), and when
``HVD_TPU_OBS_DIR`` is set each sampled point is ALSO appended to a
per-rank JSONL file with size-based rotation, so the trajectory
survives the process and is queryable offline::

    python -m horovod_tpu.metrics history --dir $HVD_TPU_OBS_DIR

Producers: ``StepTimer.end_step`` (every training loop with telemetry),
``bench.py``'s measured window, and the fleet aggregator's per-push
fleet summaries on rank 0.  Consumers: the anomaly engine
(:mod:`horovod_tpu.metrics.anomaly`) detects drift over these points,
the CLI renders them, and ``ci/check_bench.py`` gates on the bench's
recorded trajectory instead of only its last point.

Stdlib-only, like the rest of the metrics plane.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

DEFAULT_RING_CAPACITY = 4096
DEFAULT_MAX_BYTES = 16 * 1024 * 1024
DEFAULT_SAMPLE_EVERY = 1


def _env_int(name: str, default: int) -> int:
    from horovod_tpu.common.config import env_int
    return env_int(name, default)


def obs_dir() -> str:
    """``HVD_TPU_OBS_DIR`` — empty string disables persistence (the ring
    still records).  Read live, not from the cached Config snapshot: the
    obs plane must track env changes across elastic re-init and tests
    (same rule as the diagnostics knobs, see common/config.py)."""
    from horovod_tpu.common.config import env_str
    return env_str("OBS_DIR")


class TimeSeriesRing:
    """Thread-safe bounded ring of observation points (plain dicts)."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = int(capacity) if capacity else _env_int(
            "OBS_RING_SIZE", DEFAULT_RING_CAPACITY)
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=max(self.capacity, 1))
        self._lock = threading.Lock()

    def append(self, point: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(point)

    def points(self, last_n: Optional[int] = None) -> List[dict]:
        with self._lock:
            pts = list(self._ring)
        return pts[-last_n:] if last_n else pts

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


class SeriesWriter:
    """Append-only JSONL writer with size-based rotation.

    One file per rank (``obs_rank<r>.jsonl``); when the file crosses
    ``max_bytes`` it is rotated to ``.1`` (one generation kept — the ring
    plus two file generations bound disk use regardless of run length).
    Writes are line-buffered appends; a failing disk degrades to a
    dropped point, never an exception on the training thread.
    """

    def __init__(self, directory: str, rank: int = 0,
                 max_bytes: Optional[int] = None,
                 basename: str = "obs") -> None:
        self.directory = directory
        self.rank = int(rank)
        self.max_bytes = int(max_bytes) if max_bytes else _env_int(
            "OBS_MAX_BYTES", DEFAULT_MAX_BYTES)
        self.path = os.path.join(directory,
                                 f"{basename}_rank{self.rank}.jsonl")
        self._lock = threading.Lock()
        self._fh = None
        self._written = 0
        self.dropped = 0

    def _open(self):
        os.makedirs(self.directory, exist_ok=True)
        self._fh = open(self.path, "a")
        self._written = self._fh.tell()
        return self._fh

    def write(self, point: Dict[str, Any]) -> bool:
        line = json.dumps(point, default=str) + "\n"
        with self._lock:
            try:
                fh = self._fh or self._open()
                if self._written + len(line) > self.max_bytes \
                        and self._written > 0:
                    fh.close()
                    os.replace(self.path, self.path + ".1")
                    fh = self._open()
                fh.write(line)
                fh.flush()
                self._written += len(line)
                return True
            except OSError:
                self.dropped += 1  # history must never break training
                return False

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def read_series(directory: str, rank: Optional[int] = None,
                basename: str = "obs") -> List[dict]:
    """Read back the persisted trajectory, rotated generation first so
    points come out in recording order.  ``rank=None`` reads every
    rank's file, points tagged with their source rank and sorted by
    timestamp.  Torn trailing lines (a crash mid-append) are skipped."""
    out: List[dict] = []
    if rank is not None:
        names = [f"{basename}_rank{rank}.jsonl"]
    else:
        try:
            names = sorted(n for n in os.listdir(directory)
                           if n.startswith(basename + "_rank")
                           and n.endswith(".jsonl"))
        except OSError:
            return out
    for name in names:
        path = os.path.join(directory, name)
        try:
            r = int(name[len(basename + "_rank"):-len(".jsonl")])
        except ValueError:
            r = -1
        for p in (path + ".1", path):
            try:
                with open(p) as f:
                    for line in f:
                        try:
                            pt = json.loads(line)
                        except ValueError:
                            continue  # torn tail line
                        pt.setdefault("rank", r)
                        out.append(pt)
            except OSError:
                continue
    if rank is None:
        out.sort(key=lambda p: p.get("ts", 0.0))
    return out


class StepSeriesRecorder:
    """The glue between the step clock and the history layer: ring
    always, JSONL when ``HVD_TPU_OBS_DIR`` is set, sampling every
    ``HVD_TPU_OBS_SAMPLE_EVERY``-th step (default 1)."""

    def __init__(self, rank: Optional[int] = None,
                 directory: Optional[str] = None,
                 ring: Optional[TimeSeriesRing] = None) -> None:
        self.ring = ring or TimeSeriesRing()
        self.sample_every = max(
            1, _env_int("OBS_SAMPLE_EVERY", DEFAULT_SAMPLE_EVERY))
        d = obs_dir() if directory is None else directory
        if rank is None:
            from horovod_tpu.diagnostics.flight_recorder import (
                _best_effort_rank)
            rank = _best_effort_rank()
        self.rank = rank
        self.writer = SeriesWriter(d, rank=rank) if d else None
        self._n = 0

    def record_step(self, step: int, seconds: float,
                    units: float = 0.0, **extra: Any) -> Optional[dict]:
        """Record one completed step; returns the point when it was
        sampled (None when skipped by the sampling stride)."""
        self._n += 1
        if (self._n - 1) % self.sample_every:
            return None
        point = {"ts": round(time.time(), 3), "step": int(step),
                 "step_time_s": round(float(seconds), 6)}
        if units:
            point["units"] = units
            if seconds > 0:
                point["units_per_s"] = round(units / seconds, 3)
        for k, v in extra.items():
            if v is not None:
                point[k] = v
        self.ring.append(point)
        if self.writer is not None:
            self.writer.write(point)
        return point

    def close(self) -> None:
        if self.writer is not None:
            self.writer.close()


_RECORDER: Optional[StepSeriesRecorder] = None
_RECORDER_LOCK = threading.Lock()


def recorder() -> StepSeriesRecorder:
    """The process-wide step-series recorder (created on first use;
    :func:`reset` rebuilds it — an elastic re-mesh can change rank and
    ``HVD_TPU_OBS_DIR``)."""
    global _RECORDER
    if _RECORDER is None:
        with _RECORDER_LOCK:
            if _RECORDER is None:
                _RECORDER = StepSeriesRecorder()
    return _RECORDER


def record_step(step: int, seconds: float, units: float = 0.0,
                **extra: Any) -> None:
    """Module-level convenience for the instrumented call sites
    (``StepTimer.end_step``, bench's measured window); never raises."""
    try:
        recorder().record_step(step, seconds, units, **extra)
    except Exception:
        pass


def record_point(point: Dict[str, Any]) -> None:
    """Free-form observability point riding the same ring + JSONL store
    as step points — used by the re-mesh timeline
    (:mod:`horovod_tpu.elastic.remesh`) to persist each recovery
    episode's phase breakdown (``python -m horovod_tpu.metrics history
    --remesh`` renders them).  Never raises."""
    try:
        r = recorder()
        doc = dict(point)
        doc.setdefault("ts", round(time.time(), 3))
        r.ring.append(doc)
        if r.writer is not None:
            r.writer.write(doc)
    except Exception:
        pass


def reset() -> None:
    """Drop the process-wide recorder so the next use re-reads rank and
    env (elastic re-init, tests)."""
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is not None:
            _RECORDER.close()
        _RECORDER = None
