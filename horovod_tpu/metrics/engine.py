"""Derived view over the engine's control-plane counters.

The C++ core exports raw monotonic counters (``hvd_counters_json`` →
``hvd.counters()``: cycles, cache hits/misses/evictions, fused units,
bytes moved). This module turns them into the rates and ratios an operator
actually watches — cache-hit rate, fusion efficiency, bytes/s — and mirrors
the raw counters into the registry so one ``/metrics`` scrape carries both.

Rates are computed between successive ``collect()`` calls (scrapes), so a
Prometheus server polling every 15s sees 15s-window rates without the
engine keeping any windowed state.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from horovod_tpu.metrics.registry import Registry, default_registry

# engine counter -> rate gauge derived from its delta
_RATE_KEYS = ("bytes_allreduced", "bytes_allgathered", "responses_executed")


def derived_ratios(c: Dict[str, float]) -> Dict[str, float]:
    """Pure ratios from one cumulative counters dict (no windowing):
    ``cache_hit_rate`` (hits / negotiated submissions), ``fusion_ratio``
    (fraction of executed responses that were multi-tensor units) and
    ``tensors_per_fused_unit``."""
    out: Dict[str, float] = {}
    hits = float(c.get("cache_hits", 0))
    misses = float(c.get("cache_misses", 0))
    if hits + misses > 0:
        out["cache_hit_rate"] = hits / (hits + misses)
    executed = float(c.get("responses_executed", 0))
    fused_units = float(c.get("fused_units", 0))
    if executed > 0:
        out["fusion_ratio"] = fused_units / executed
    tensors_fused = float(c.get("tensors_fused", 0))
    if fused_units > 0:
        out["tensors_per_fused_unit"] = tensors_fused / fused_units
    return out


class EngineCollector:
    """Scrape-time collector: pulls ``counters_fn()`` (and optionally
    ``stragglers_fn()``), refreshes ``hvd_engine_*`` metrics in the
    registry. Safe to call when the engine is not initialized — a failing
    or empty pull leaves the previous values in place."""

    def __init__(self, counters_fn: Callable[[], dict],
                 registry: Optional[Registry] = None,
                 stragglers_fn: Optional[Callable[[], dict]] = None
                 ) -> None:
        self._counters_fn = counters_fn
        self._stragglers_fn = stragglers_fn
        self._reg = registry or default_registry()
        self._prev: Optional[Dict[str, float]] = None
        self._prev_t = 0.0

    def collect(self) -> None:
        try:
            c = self._counters_fn()
        except Exception:
            return
        now = time.monotonic()
        if c:
            for key, val in c.items():
                if key.startswith("autotune_"):
                    # live tuner decisions (fusion bytes, cycle ms,
                    # hierarchical/cache flips) are config VALUES, not
                    # cumulative counters: first-class hvd_autotune_*
                    # gauges, max-merged (every rank mirrors the same
                    # coordinator-tuned value) — docs/OBSERVABILITY.md
                    # "Autotune metrics"
                    sub = key[len("autotune_"):]
                    self._reg.gauge(
                        f"hvd_autotune_{sub}",
                        help=f"engine autotune decision: {sub}",
                        agg="max").set(float(val))
                    continue
                self._reg.gauge(
                    f"hvd_engine_{key}",
                    help=f"engine counter {key} (cumulative)",
                    agg="sum").set(float(val))
            # stall inspector surfaced as first-class metrics (beyond
            # the generic hvd_engine_* mirror): a true Prometheus
            # counter for warnings plus the live stalled-tensor gauge
            # (docs/OBSERVABILITY.md "Stall metrics")
            if "stall_warnings" in c:
                counter = self._reg.counter(
                    "hvd_stall_warnings_total",
                    help="stall-inspector warnings issued (tensors that "
                         "crossed STALL_CHECK_TIME_SECONDS)")
                cur = float(c["stall_warnings"])
                prev_sw = (self._prev or {}).get("stall_warnings")
                if prev_sw is None:
                    # first sample from this collector: sync against the
                    # registry total (another collector generation may
                    # already have recorded part of it)
                    delta = cur - counter.value
                else:
                    delta = cur - float(prev_sw)
                if delta < 0:
                    # engine restarted (elastic re-mesh resets the C++
                    # counters): the whole new total is new warnings
                    delta = cur
                if delta > 0:
                    counter.inc(delta)
            if "stalled_tensors" in c:
                self._reg.gauge(
                    "hvd_stalled_tensors",
                    help="tensors currently past the stall warning "
                         "threshold", agg="sum").set(
                    float(c["stalled_tensors"]))
            for key, val in derived_ratios(c).items():
                self._reg.gauge(
                    f"hvd_engine_{key}",
                    help=f"engine derived ratio {key}",
                    agg="mean").set(val)
            if self._prev is not None and now > self._prev_t:
                dt = now - self._prev_t
                for key in _RATE_KEYS:
                    if key in c and key in self._prev:
                        delta = float(c[key]) - float(self._prev[key])
                        self._reg.gauge(
                            f"hvd_engine_{key}_per_second",
                            help=f"engine {key} rate over the last "
                                 "scrape interval",
                            agg="sum").set(max(delta, 0.0) / dt)
            self._prev, self._prev_t = dict(c), now
        if self._stragglers_fn is None:
            return
        try:
            s = self._stragglers_fn()
        except Exception:
            return
        for rank, info in (s.get("ranks") or {}).items():
            self._reg.gauge(
                "hvd_straggler_wait_seconds",
                help="total negotiation wait attributed to this rank "
                     "being last to announce",
                labels={"rank": str(rank)}, agg="max").set(
                float(info.get("wait_seconds", 0.0)))
            self._reg.gauge(
                "hvd_straggler_held_count",
                help="tensors for which this rank was the last announcer",
                labels={"rank": str(rank)}, agg="max").set(
                float(info.get("held_count", 0)))
