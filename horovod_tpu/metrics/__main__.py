"""Fleet observability CLI: ``python -m horovod_tpu.metrics <cmd>``.

* ``top`` — live, curses-free fleet dashboard: polls one endpoint
  (rank 0's ``/metrics/fleet`` by default, falling back to plain
  ``/metrics``) and renders the headline numbers plus the per-rank
  step-time breakdown as plain text, redrawn in place with ANSI
  escapes (``--once`` / ``--iterations`` for scripting).
* ``history`` — tabular dump of the persisted step time-series
  (``HVD_TPU_OBS_DIR`` JSONL, docs/OBSERVABILITY.md "Step time-series
  history"); plot-free by design — pipe into your tool of choice.
  ``--remesh`` renders the re-mesh phase table, ``--actions`` the
  autopilot decision audit trail ("my job re-meshed itself — why?"
  starts here, docs/TROUBLESHOOTING.md).

Both are stdlib-only, like everything else in the metrics plane.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import Dict, Optional

from horovod_tpu.metrics.timeseries import read_series


def _fetch(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def parse_prometheus(text: str) -> Dict[str, float]:
    """Minimal text-format v0.0.4 parser: {series_key: value}."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(" ", 1)
            out[key] = float(val)
        except ValueError:
            continue
    return out


def _labeled(series: Dict[str, float], name: str) -> Dict[str, float]:
    """{label-suffix: value} for every series of ``name{...}``."""
    out = {}
    for key, v in series.items():
        if key.startswith(name + "{") and key.endswith("}"):
            out[key[len(name) + 1:-1]] = v
    return out


def _fmt_seconds(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v * 1e3:.1f}ms" if v < 1.0 else f"{v:.2f}s"


def _fmt_bytes(v: Optional[float]) -> str:
    if v is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024:
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024
    return f"{v:.1f}TiB"


def render_top(series: Dict[str, float], source: str) -> str:
    """One dashboard frame from a parsed scrape (pure: unit-testable)."""
    lines = [f"hvd-tpu fleet view  [{source}]  "
             f"{time.strftime('%H:%M:%S')}"]
    size = series.get("hvd_fleet_size")
    reporting = series.get("hvd_fleet_ranks_reporting")
    if size is not None:
        gap = "" if reporting == size else "  << RANKS MISSING"
        lines.append(f"ranks reporting : {int(reporting or 0)}/{int(size)}"
                     f" (tree depth {int(series.get('hvd_fleet_tree_depth', 0))},"
                     f" generation {int(series.get('hvd_fleet_generation', 0))})"
                     + gap)
    steps = series.get("hvd_steps_total")
    if steps is not None:
        lines.append(f"steps total     : {int(steps)}")
    tsum = series.get("hvd_step_time_seconds_sum")
    tcnt = series.get("hvd_step_time_seconds_count")
    if tcnt:
        lines.append(f"step time mean  : {_fmt_seconds(tsum / tcnt)} "
                     f"(over {int(tcnt)} samples)")
    mn, mx = series.get("hvd_fleet_step_time_min"), \
        series.get("hvd_fleet_step_time_max")
    if mn is not None and mx is not None:
        lines.append(
            f"step time window: min {_fmt_seconds(mn)}  "
            f"mean {_fmt_seconds(series.get('hvd_fleet_step_time_mean'))}  "
            f"max {_fmt_seconds(mx)}")
    straggler = series.get("hvd_fleet_straggler_rank")
    if straggler is not None:
        lines.append(f"straggler rank  : {int(straggler)}")
    # HBM view (docs/OBSERVABILITY.md "Compile & memory observability"):
    # in-use/peak merge max over ranks, the OOM margin merges MIN — the
    # tightest rank is the number that matters
    in_use = series.get("hvd_hbm_bytes_in_use")
    if in_use is not None:
        margin = series.get("hvd_hbm_oom_margin_bytes")
        lines.append(
            f"hbm             : {_fmt_bytes(in_use)} in use, "
            f"peak {_fmt_bytes(series.get('hvd_hbm_peak_bytes'))} / "
            f"limit {_fmt_bytes(series.get('hvd_hbm_limit_bytes'))}"
            + (f"  (OOM margin {_fmt_bytes(margin)})"
               if margin is not None else ""))
    # compile view: total backend compiles + tracing-cache misses +
    # compile seconds (histogram _sum summed across function labels)
    compiles = series.get("hvd_compile_total")
    if compiles is not None:
        misses = series.get("hvd_compile_cache_miss_total")
        secs = sum(v for k, v in series.items()
                   if k.startswith("hvd_compile_seconds_sum"))
        detail = [f"{_fmt_seconds(secs)} total"]
        if misses is not None:
            detail.insert(0, f"{int(misses)} cache misses")
        lines.append(f"compiles        : {int(compiles)} "
                     f"({', '.join(detail)})")
    remeshes = series.get("hvd_remesh_total")
    if remeshes:
        rsecs = sum(v for k, v in series.items()
                    if k.startswith("hvd_remesh_seconds_sum"))
        lines.append(f"re-meshes       : {int(remeshes)} "
                     f"({_fmt_seconds(rsecs)} total recovery)")
    # control-plane HA (docs/ELASTIC.md "Driver failover & takeover"):
    # worst-rank outage age, takeover count, journal footprint
    outage_age = series.get("hvd_driver_outage_seconds")
    takeovers = series.get("hvd_driver_takeovers_total")
    jbytes = series.get("hvd_driver_journal_bytes")
    if outage_age or takeovers or jbytes:
        line = (f"DRIVER          : outage "
                f"{_fmt_seconds(outage_age or 0.0)}  "
                f"takeovers {int(takeovers or 0)}")
        if jbytes is not None:
            line += (f"  journal {_fmt_bytes(jbytes)} "
                     f"({int(series.get('hvd_driver_journal_records', 0))}"
                     f" records)")
        if outage_age:
            line += "  << DRIVER UNREACHABLE"
        lines.append(line)
    # goodput ledger (docs/OBSERVABILITY.md "Goodput ledger"): the
    # fleet-summed per-category seconds as fractions of accounted wall
    # time, plus the worst rank's productive fraction
    goodput = _labeled(series, "hvd_goodput_seconds_total")
    if goodput:
        total = sum(goodput.values())
        cats = {k.split('=')[1].strip(chr(34)): v
                for k, v in goodput.items()}
        productive = cats.get("compute", 0.0) / total if total else 0.0
        loss = sorted(((c, v / total) for c, v in cats.items()
                       if c != "compute" and total and v > 0),
                      key=lambda cv: -cv[1])
        detail = ", ".join(f"{c} {f:.1%}" for c, f in loss[:4])
        line = (f"GOODPUT         : {productive:.1%} productive"
                + (f"  ({detail})" if detail else ""))
        worst_rank = series.get("hvd_fleet_goodput_worst_rank")
        worst = series.get("hvd_fleet_goodput_min")
        if worst_rank is not None and worst is not None:
            line += f"  worst rank {int(worst_rank)} @ {worst:.1%}"
        lines.append(line)
    # serving view (docs/SERVING.md): the windowed SLO signal plus the
    # robustness counters — sheds are EXPLICIT 429s, hedges/retries are
    # requests that survived a slow or dead replica
    qps = series.get("hvd_serving_qps")
    accepted = series.get("hvd_serving_accepted_total")
    if qps is not None or accepted is not None:
        shed = sum(v for k, v in series.items()
                   if k.startswith("hvd_serving_shed_total"))
        lines.append(
            f"SERVING         : {qps or 0.0:,.1f} qps  "
            f"queue {int(series.get('hvd_serving_queue_depth', 0))}  "
            f"p50 {_fmt_seconds(series.get('hvd_serving_p50_seconds'))}  "
            f"p99 {_fmt_seconds(series.get('hvd_serving_p99_seconds'))}  "
            f"shed {int(shed)}  "
            f"hedged {int(series.get('hvd_serving_hedged_total', 0))}  "
            f"retried {int(series.get('hvd_serving_retried_total', 0))}")
        replicas = series.get("hvd_serving_replicas_live")
        if replicas is not None:
            target = series.get("hvd_serving_replicas_target", replicas)
            gap = "" if replicas >= target else "  << FLEET BELOW TARGET"
            lines.append(
                f"replicas        : {int(replicas)}/{int(target)} ready"
                f" (weights v{int(series.get('hvd_serving_weight_version', 0))},"
                f" {int(series.get('hvd_serving_swaps_total', 0))} swaps,"
                f" {int(series.get('hvd_serving_replica_respawns_total', 0))}"
                f" respawns)" + gap)
    for key, value in sorted(series.items()):
        if key.endswith("_per_second") and "{" not in key:
            lines.append(f"{key[4:]:<16}: {value:,.1f}")
    anomalies = _labeled(series, "hvd_anomaly_total")
    if anomalies:
        kinds = ", ".join(f"{k.split('=')[1].strip(chr(34))}×{int(v)}"
                          for k, v in sorted(anomalies.items()))
        lines.append(f"ANOMALIES       : {kinds}")
    # autopilot decisions (docs/OBSERVABILITY.md "Autopilot"): the
    # per-policy/outcome counters plus the mode, one line — the full
    # audit trail is `history --actions`
    decisions = _labeled(series, "hvd_autopilot_decisions_total")
    mode_v = series.get("hvd_autopilot_mode")
    if decisions or mode_v is not None:
        mode_name = {0: "off", 1: "observe", 2: "act"}.get(
            int(mode_v) if mode_v is not None else 1, "?")
        cells = []
        for labels, v in sorted(decisions.items()):
            parts = dict(p.split("=", 1) for p in labels.split(","))
            cells.append(
                f"{parts.get('policy', '?').strip(chr(34))} "
                f"{parts.get('outcome', '?').strip(chr(34))}×{int(v)}")
        lines.append(f"AUTOPILOT [{mode_name}]: "
                     + (", ".join(cells) if cells else "no decisions"))
    per_rank = _labeled(series, "hvd_fleet_rank_step_time_seconds")
    if per_rank:
        lines.append("per-rank windowed step time:")
        entries = sorted(per_rank.items(),
                         key=lambda kv: int(kv[0].split('"')[1]))
        worst = max(v for _, v in entries)
        for label, v in entries:
            r = label.split('"')[1]
            bar = "#" * max(1, int(30 * v / worst)) if worst > 0 else ""
            lines.append(f"  rank {r:>4}  {_fmt_seconds(v):>9}  {bar}")
    return "\n".join(lines)


def cmd_top(args: argparse.Namespace) -> int:
    url = args.url.rstrip("/")
    endpoints = [url] if url.endswith(("/metrics", "/metrics/fleet")) \
        else [url + "/metrics/fleet", url + "/metrics"]
    iterations = 1 if args.once else args.iterations
    n = 0
    while iterations <= 0 or n < iterations:
        n += 1
        body = source = None
        for ep in endpoints:
            try:
                body, source = _fetch(ep), ep
                break
            except Exception as e:
                err = e
        if body is None:
            print(f"scrape failed: {err!r}", file=sys.stderr)
            return 1
        frame = render_top(parse_prometheus(body), source)
        if n > 1:
            # redraw in place: cursor home + clear-to-end (curses-free)
            sys.stdout.write("\x1b[H\x1b[J")
        elif iterations != 1:
            sys.stdout.write("\x1b[2J\x1b[H")
        print(frame, flush=True)
        if iterations <= 0 or n < iterations:
            time.sleep(args.interval)
    return 0


REMESH_PHASES = ("failure_detect", "drain", "rendezvous", "rebuild",
                 "restore", "first_step")


def render_remesh_table(points) -> str:
    """The re-mesh phase table (docs/OBSERVABILITY.md "Re-mesh
    timeline"): one row per recovery episode found in the persisted
    series, phase seconds in pipeline order."""
    rows = [p for p in points if isinstance(p.get("remesh"), dict)]
    if not rows:
        return ""
    head = (f"{'ts':<19} {'rank':>4} {'trigger':<16} "
            + " ".join(f"{c:>14}" for c in REMESH_PHASES)
            + f" {'total':>10}")
    lines = [head]
    for p in rows:
        phases = p["remesh"]
        ts = time.strftime("%Y-%m-%d %H:%M:%S",
                           time.localtime(p.get("ts", 0)))
        cells = " ".join(
            f"{_fmt_seconds(phases.get(c)):>14}" for c in REMESH_PHASES)
        # an episode that healed across a driver takeover is a
        # control-plane recovery, not a data-plane one — say so
        trig = str(p.get("trigger", "-"))
        if p.get("takeover"):
            trig += "+takeover"
        lines.append(
            f"{ts:<19} {p.get('rank', '-'):>4} "
            f"{trig:<16} {cells} "
            f"{_fmt_seconds(p.get('remesh_total_s')):>10}")
    spanned = sum(1 for p in rows if p.get("takeover"))
    lines.append(f"-- {len(rows)} re-mesh episode(s)"
                 + (f", {spanned} spanning a driver takeover"
                    if spanned else ""))
    return "\n".join(lines)


def render_actions_table(decisions) -> str:
    """The autopilot decision audit table (docs/OBSERVABILITY.md
    "Autopilot"): one row per recorded decision — fired, dry-run, or
    suppressed — with the gate input that mattered."""
    head = (f"{'ts':<19} {'rank':>4} {'policy':<20} {'action':<18} "
            f"{'finding':<22} {'outcome':<10} {'reason/gate'}")
    lines = [head]
    for d in decisions:
        ts = time.strftime("%Y-%m-%d %H:%M:%S",
                           time.localtime(d.get("ts", 0)))
        gate = d.get("gate") or {}
        detail = d.get("reason", "")
        extras = []
        if d.get("target_rank") is not None:
            extras.append(f"target_rank={d['target_rank']}")
        if d.get("key") is not None:
            extras.append(f"key={d['key']}")
        for k in ("remesh_p50_s", "projected_loss_s", "margin_frac",
                  "cooldown_remaining_s", "actions_in_window"):
            if gate.get(k) is not None:
                extras.append(f"{k}={gate[k]}")
        if d.get("trace"):
            # the causal join key: paste it into
            # `python -m horovod_tpu.diagnostics trace <id>`
            extras.append(f"trace={d['trace'][:12]}")
        if extras:
            detail = (detail + " " if detail else "") + " ".join(extras)
        lines.append(
            f"{ts:<19} {str(d.get('rank', '-')):>4} "
            f"{str(d.get('policy', '-')):<20} "
            f"{str(d.get('action', '-')):<18} "
            f"{str(d.get('finding', '-')):<22} "
            f"{str(d.get('outcome', '-')):<10} {detail}")
    lines.append(f"-- {len(decisions)} decision(s)")
    return "\n".join(lines)


def render_serving_table(points) -> str:
    """The per-window serving latency series (docs/SERVING.md): one row
    per closed :class:`~horovod_tpu.serving.metrics.LatencyWindow` —
    the trajectory behind "my p99 spiked" (docs/TROUBLESHOOTING.md).
    Windows observed with the request ledger also say WHERE the window
    went: the dominant stage with its share, and the unattributed
    residual fraction (the books-close check, live)."""
    head = (f"{'ts':<19} {'rank':>4} {'window':>8} {'requests':>9} "
            f"{'qps':>9} {'p50':>10} {'p99':>10} {'shed':>6} "
            f"{'dominant':<16} {'unattr':>7}")
    lines = [head]
    for p in points:
        w = p["serving"]
        ts = time.strftime("%Y-%m-%d %H:%M:%S",
                           time.localtime(p.get("ts", 0)))
        dom = w.get("dominant_stage") or "-"
        share = (w.get("stage_shares") or {}).get(dom)
        if share is not None:
            dom = f"{dom} {share * 100:.0f}%"
        unattr = w.get("unattributed_frac")
        unattr_s = f"{unattr * 100:.1f}%" if unattr is not None else "-"
        lines.append(
            f"{ts:<19} {str(p.get('rank', '-')):>4} "
            f"{w.get('window_s', 0):>7.1f}s {w.get('requests', 0):>9} "
            f"{w.get('qps', 0):>9.1f} "
            f"{_fmt_seconds(w.get('p50_s')):>10} "
            f"{_fmt_seconds(w.get('p99_s')):>10} "
            f"{w.get('shed', 0):>6} "
            f"{dom:<16} {unattr_s:>7}")
    lines.append(f"-- {len(points)} serving window(s)")
    return "\n".join(lines)


GOODPUT_CATEGORIES = ("compute", "exposed_comm", "compile",
                      "remesh_recovery", "checkpoint_stall", "input_wait",
                      "guard_skipped", "idle_other")


def render_goodput_table(points) -> str:
    """The per-window goodput category table (docs/OBSERVABILITY.md
    "Goodput ledger"): one row per closed ledger window, category
    seconds in fixed order plus the window's productive fraction and
    whether its books closed."""
    head = (f"{'ts':<19} {'rank':>4} {'steps':>6} {'wall':>9} "
            + " ".join(f"{c[:10]:>10}" for c in GOODPUT_CATEGORIES)
            + f" {'frac':>6} {'books':>6}")
    lines = [head]
    for p in points:
        w = p["goodput"]
        ts = time.strftime("%Y-%m-%d %H:%M:%S",
                           time.localtime(p.get("ts", 0)))
        cells = " ".join(f"{_fmt_seconds(w.get(c, 0.0)):>10}"
                         for c in GOODPUT_CATEGORIES)
        frac = p.get("goodput_fraction")
        lines.append(
            f"{ts:<19} {str(p.get('rank', '-')):>4} "
            f"{p.get('goodput_steps', '-'):>6} "
            f"{_fmt_seconds(p.get('goodput_wall_s')):>9} {cells} "
            f"{frac if frac is None else format(frac, '.1%'):>6} "
            f"{'ok' if p.get('goodput_closed', True) else 'OPEN!':>6}")
    lines.append(f"-- {len(points)} goodput window(s)")
    return "\n".join(lines)


def cmd_history(args: argparse.Namespace) -> int:
    if getattr(args, "goodput", False):
        points = [p for p in read_series(args.dir, rank=args.rank)
                  if isinstance(p.get("goodput"), dict)]
        if args.last:
            points = points[-args.last:]
        if not points:
            print(f"no goodput windows recorded under {args.dir}",
                  file=sys.stderr)
            return 1
        if args.json:
            for p in points:
                print(json.dumps(p))
            return 0
        print(render_goodput_table(points))
        return 0
    if getattr(args, "serving", False):
        points = [p for p in read_series(args.dir, rank=args.rank)
                  if isinstance(p.get("serving"), dict)]
        if args.last:
            points = points[-args.last:]
        if not points:
            print(f"no serving windows recorded under {args.dir}",
                  file=sys.stderr)
            return 1
        if args.json:
            for p in points:
                print(json.dumps(p))
            return 0
        print(render_serving_table(points))
        return 0
    if getattr(args, "actions", False):
        # the autopilot action log rides its own JSONL files
        # (actions_rank<r>.jsonl) in the same store
        decisions = read_series(args.dir, rank=args.rank,
                                basename="actions")
        if args.last:
            decisions = decisions[-args.last:]
        if not decisions:
            print(f"no autopilot decisions recorded under {args.dir}",
                  file=sys.stderr)
            return 1
        if args.json:
            for d in decisions:
                print(json.dumps(d))
            return 0
        print(render_actions_table(decisions))
        return 0
    points = read_series(args.dir, rank=args.rank)
    if getattr(args, "remesh", False):
        episodes = [p for p in points if isinstance(p.get("remesh"), dict)]
        if args.last:
            episodes = episodes[-args.last:]
        if not episodes:
            print(f"no re-mesh episodes recorded under {args.dir}",
                  file=sys.stderr)
            return 1
        if args.json:
            for p in episodes:
                print(json.dumps(p))
            return 0
        print(render_remesh_table(episodes))
        return 0
    # step points only: free-form episode points have their own view
    points = [p for p in points if "remesh" not in p
              and "serving" not in p and "goodput" not in p]
    if args.last:
        points = points[-args.last:]
    if not points:
        print(f"no series under {args.dir}", file=sys.stderr)
        return 1
    if args.json:
        for p in points:
            print(json.dumps(p))
        return 0
    cols = ["rank", "step", "step_time_s", "units_per_s"]
    print(f"{'ts':<19} " + " ".join(f"{c:>12}" for c in cols))
    for p in points:
        ts = time.strftime("%Y-%m-%d %H:%M:%S",
                           time.localtime(p.get("ts", 0)))
        row = " ".join(
            f"{p[c]:>12}" if c in p else f"{'-':>12}" for c in cols)
        print(f"{ts:<19} {row}")
    print(f"-- {len(points)} point(s)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m horovod_tpu.metrics",
                                description=__doc__.split("\n\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    t = sub.add_parser("top", help="live fleet dashboard")
    t.add_argument("--url", default="http://127.0.0.1:9090",
                   help="exporter base URL (rank 0); /metrics/fleet is "
                        "tried first, /metrics as fallback")
    t.add_argument("--interval", type=float, default=2.0)
    t.add_argument("--iterations", type=int, default=0,
                   help="frames to render (0 = until interrupted)")
    t.add_argument("--once", action="store_true",
                   help="render one frame and exit")
    t.set_defaults(fn=cmd_top)
    h = sub.add_parser("history", help="dump the persisted time-series")
    h.add_argument("--dir", required=True, help="HVD_TPU_OBS_DIR")
    h.add_argument("--rank", type=int, default=None,
                   help="one rank's series (default: all, time-sorted)")
    h.add_argument("--last", type=int, default=0,
                   help="only the last N points")
    h.add_argument("--json", action="store_true",
                   help="raw JSONL instead of the table")
    h.add_argument("--remesh", action="store_true",
                   help="render the re-mesh phase table instead of the "
                        "step series (one row per recovery episode)")
    h.add_argument("--actions", action="store_true",
                   help="render the autopilot decision audit trail "
                        "(actions_rank<r>.jsonl) instead of the step "
                        "series — one row per fired/dry-run/suppressed "
                        "decision")
    h.add_argument("--serving", action="store_true",
                   help="render the per-window serving latency series "
                        "(qps, p50/p99, shed) instead of the step "
                        "series — one row per closed latency window")
    h.add_argument("--goodput", action="store_true",
                   help="render the per-window goodput category table "
                        "(wall seconds per category, productive "
                        "fraction, books-closed flag) instead of the "
                        "step series — one row per closed ledger window")
    h.set_defaults(fn=cmd_history)
    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    sys.exit(main())
