"""Online anomaly engine: EWMA+MAD detectors over the step time-series.

The live metrics answer *is the job healthy now*; the autopsy answers
*what happened when it died*; this layer answers the question between
them: **was it degrading before anyone noticed?**  Four detectors run
over the points the time-series layer records
(:mod:`horovod_tpu.metrics.timeseries`):

* ``step_time_drift`` — step wall time drifts above its rolling
  baseline (an EWMA with a MAD-style robust deviation estimate);
* ``throughput_regression`` — units/s falls below the rolling baseline;
* ``exposed_comm_growth`` — the exposed-communication fraction of the
  step (``hvd_overlap_exposed_comm_seconds`` / step time) grows — the
  overlap schedule is losing (docs/PERF.md "Overlap & bucketing");
* ``persistent_straggler`` — the fleet view charges the SAME rank as
  slowest for N consecutive aggregation windows (fed by the fleet
  aggregator on rank 0, :mod:`horovod_tpu.metrics.fleet`);
* ``goodput_regression`` — the goodput ledger's productive (compute)
  fraction falls below its rolling baseline (fed once per closed
  ledger window, :mod:`horovod_tpu.metrics.goodput`); the finding
  names the dominating loss category.

Three serving detectors ride the request ledger's closed windows
(:mod:`horovod_tpu.serving.ledger`, fed once per ``LatencyWindow``
roll via :func:`observe_serving_window`) and let the autopilot tell a
scale-out-shaped breach from a swap/KV-shaped one:

* ``ttft_drift`` — windowed time-to-first-token p50 drifts above its
  rolling baseline (generate traffic only);
* ``queue_growth`` — the queueing stages (``queue`` + ``batch_wait``)
  take over the request wall-clock: their windowed stage share stays
  over ``HVD_TPU_SERVING_QUEUE_SHARE`` — the scale-out-shaped signal;
* ``kv_thrash`` — the ``page_wait`` stage share stays over
  ``HVD_TPU_SERVING_KV_THRASH_SHARE``: sequences starve for KV pages,
  which more replicas will NOT fix (grow the pool / shrink worst-case
  budgets instead).

Every finding lands three ways: a ``hvd_anomaly_total{kind=...}``
counter on ``/metrics``, an ``anomaly`` flight-recorder event, and the
engine's bounded findings list, which the autopsy bundle's summary
embeds — a hang autopsy now says whether the job was already sick.

Detection is deliberately conservative (the acceptance bar is ZERO
false positives on a clean run): a point is anomalous only when it is
``k`` robust deviations AND a minimum ratio away from the baseline, it
takes ``consecutive`` anomalous points in a row to flag, the baseline
refuses to learn from anomalous points (a stall must not become the new
normal), and a flagged detector stays quiet until the signal recovers
(hysteresis — one finding per episode, not one per step).

Thresholds are env-tunable (docs/KNOBS.md): ``HVD_TPU_ANOMALY_ALPHA``,
``_K``, ``_MIN_RATIO``, ``_CONSECUTIVE``, ``_WARMUP``,
``_STRAGGLER_WINDOWS``, ``_STRAGGLER_RATIO``; ``HVD_TPU_ANOMALY=0``
disables the engine entirely.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from horovod_tpu.metrics.registry import Registry, default_registry

MAX_FINDINGS = 64


def _envf(name: str, default: float) -> float:
    from horovod_tpu.common.config import env_float
    return env_float(name, default)


def _envi(name: str, default: int) -> int:
    from horovod_tpu.common.config import env_int
    return env_int(name, default)


def enabled() -> bool:
    from horovod_tpu.common.config import env_bool
    return env_bool("ANOMALY", True)


class EwmaMad:
    """Robust online baseline: an EWMA of the value plus an EWMA of the
    absolute residual (a MAD-flavored scale estimate — resistant to the
    occasional spike a variance estimate would chase).  The deviation is
    floored at ``rel_floor`` of the mean plus ``abs_floor`` so a
    near-constant series (CPU smoke steps jitter by microseconds) does
    not become hypersensitive."""

    def __init__(self, alpha: float, rel_floor: float = 0.05,
                 abs_floor: float = 1e-6) -> None:
        self.alpha = alpha
        self.rel_floor = rel_floor
        self.abs_floor = abs_floor
        self.mean: Optional[float] = None
        self.mad = 0.0
        self.n = 0

    def update(self, v: float) -> None:
        self.n += 1
        if self.mean is None:
            self.mean = v
            return
        # Bias-corrected warmup: early on, weight new points as a plain
        # sample mean (1/n) instead of the steady-state alpha.  A slow
        # alpha otherwise lags the mean for the whole warmup ramp and
        # the MAD learns that LAG as if it were noise — a first window
        # skewed by compile then inflates k*dev past the entire value
        # range, hiding even an 80% drop from the drift rule.
        a = max(self.alpha, 1.0 / self.n)
        resid = abs(v - self.mean)
        self.mean += a * (v - self.mean)
        self.mad += a * (resid - self.mad)

    def deviation(self) -> float:
        m = abs(self.mean or 0.0)
        return max(self.mad, self.rel_floor * m, self.abs_floor)


class _DriftDetector:
    """Shared one-sided drift rule: warmup, then flag after
    ``consecutive`` points beyond ``k`` deviations AND ``min_ratio``
    from the baseline, with hysteresis and baseline freezing while
    anomalous.  ``direction=+1`` flags increases (step time),
    ``-1`` decreases (throughput)."""

    def __init__(self, kind: str, direction: int, alpha: float, k: float,
                 min_ratio: float, consecutive: int, warmup: int) -> None:
        self.kind = kind
        self.direction = direction
        self.baseline = EwmaMad(alpha)
        self.k = k
        self.min_ratio = min_ratio
        self.consecutive = max(1, consecutive)
        self.warmup = max(2, warmup)
        self._streak = 0
        self._active = False  # inside a flagged episode

    def observe(self, v: float) -> Optional[dict]:
        b = self.baseline
        if b.n < self.warmup:
            b.update(v)
            return None
        mean, dev = b.mean, b.deviation()
        delta = (v - mean) * self.direction
        ratio_bad = (v > mean * self.min_ratio) if self.direction > 0 \
            else (v < mean / self.min_ratio)
        anomalous = delta > self.k * dev and ratio_bad
        if not anomalous:
            b.update(v)  # only healthy points teach the baseline
            self._streak = 0
            self._active = False  # recovered: a new episode may flag
            return None
        self._streak += 1
        if self._active or self._streak < self.consecutive:
            return None
        self._active = True
        return {"kind": self.kind, "value": round(v, 6),
                "baseline": round(mean, 6),
                "deviation": round(dev, 6),
                "ratio": round(v / mean, 3) if mean else None,
                "consecutive": self._streak}


class _StageShareDetector:
    """Threshold detector over one windowed stage-share signal from the
    serving request ledger: flags after ``windows`` consecutive closed
    windows where the summed share of ``stages`` exceeds ``threshold``,
    with the same one-finding-per-episode hysteresis as the drift
    detectors.  An idle window (no requests) resets the episode — the
    condition did not survive the traffic that caused it."""

    def __init__(self, kind: str, stages: tuple, threshold: float,
                 windows: int) -> None:
        self.kind = kind
        self.stages = stages
        self.threshold = threshold
        self.windows = max(1, windows)
        self._streak = 0
        self._active = False

    def observe(self, doc: dict) -> Optional[dict]:
        if not doc.get("requests"):
            self._streak = 0
            self._active = False
            return None
        shares = doc.get("stage_shares") or {}
        share = sum(shares.get(s, 0.0) for s in self.stages)
        if share <= self.threshold:
            self._streak = 0
            self._active = False
            return None
        self._streak += 1
        if self._active or self._streak < self.windows:
            return None
        self._active = True
        worst = max(self.stages, key=lambda s: shares.get(s, 0.0))
        finding = {"kind": self.kind, "value": round(share, 4),
                   "threshold": self.threshold,
                   "dominant_stage": worst,
                   "stage_share": round(shares.get(worst, 0.0), 4),
                   "consecutive": self._streak}
        if doc.get("worst_trace"):
            finding["worst_trace"] = doc["worst_trace"]
        return finding


class AnomalyEngine:
    """Per-process detector bank; feed it from the train loop
    (``observe_step``) and, on rank 0, from the fleet aggregator
    (``observe_fleet``).  Thread-safe; every call is O(1)."""

    def __init__(self, registry: Optional[Registry] = None) -> None:
        self._reg = registry or default_registry()
        self._lock = threading.Lock()
        alpha = _envf("ANOMALY_ALPHA", 0.1)
        k = _envf("ANOMALY_K", 6.0)
        min_ratio = _envf("ANOMALY_MIN_RATIO", 1.5)
        consecutive = _envi("ANOMALY_CONSECUTIVE", 3)
        warmup = _envi("ANOMALY_WARMUP", 10)
        self._step = _DriftDetector(
            "step_time_drift", +1, alpha, k, min_ratio, consecutive,
            warmup)
        self._thr = _DriftDetector(
            "throughput_regression", -1, alpha, k, min_ratio, consecutive,
            warmup)
        self._exposed = _DriftDetector(
            "exposed_comm_growth", +1, alpha, k, min_ratio, consecutive,
            warmup)
        # goodput windows land once per HVD_TPU_GOODPUT_WINDOW steps,
        # so the same consecutive/warmup knobs span a proportionally
        # longer wall-clock learning period — deliberately: a goodput
        # regression is a sustained condition, not a blip
        self._goodput = _DriftDetector(
            "goodput_regression", -1, alpha, k, min_ratio, consecutive,
            warmup)
        # serving-plane detectors (fed per closed LatencyWindow by
        # observe_serving): TTFT drifts like step time; the stage-share
        # pair are threshold detectors — a share is already normalized,
        # a learned baseline would only blunt the "where" answer
        self._ttft = _DriftDetector(
            "ttft_drift", +1, alpha, k, min_ratio, consecutive, warmup)
        share_windows = max(1, _envi("SERVING_STAGE_WINDOWS", 2))
        self._queue_share = _StageShareDetector(
            "queue_growth", ("queue", "batch_wait"),
            _envf("SERVING_QUEUE_SHARE", 0.5), share_windows)
        self._kv_share = _StageShareDetector(
            "kv_thrash", ("page_wait",),
            _envf("SERVING_KV_THRASH_SHARE", 0.25), share_windows)
        self._straggler_windows = max(
            2, _envi("ANOMALY_STRAGGLER_WINDOWS", 3))
        self._straggler_ratio = _envf("ANOMALY_STRAGGLER_RATIO", 1.3)
        self._straggler_rank: Optional[int] = None
        self._straggler_run = 0
        self._straggler_active = False
        self.findings: List[dict] = []

    # -- feeds ---------------------------------------------------------------
    def observe_step(self, step: int, seconds: float,
                     units_per_s: Optional[float] = None,
                     exposed_comm_s: Optional[float] = None) -> List[dict]:
        """One completed step; returns any NEW findings (usually [])."""
        out = []
        with self._lock:
            f = self._step.observe(float(seconds))
            if f:
                out.append(self._flag(f, step=step))
            if units_per_s is not None and units_per_s > 0:
                f = self._thr.observe(float(units_per_s))
                if f:
                    out.append(self._flag(f, step=step))
            if exposed_comm_s is not None and seconds > 0:
                frac = max(0.0, min(1.0, exposed_comm_s / seconds))
                f = self._exposed.observe(frac)
                if f:
                    out.append(self._flag(f, step=step))
        return out

    def observe_goodput(self, fraction: float,
                        dominating: Optional[str] = None) -> List[dict]:
        """One closed goodput-ledger window: the productive (compute)
        fraction of wall time (docs/OBSERVABILITY.md "Goodput ledger").
        A sustained drop below the learned baseline flags a
        ``goodput_regression`` finding naming the category that now
        dominates the loss — the anomaly→profile hook captures a device
        trace of exactly the regressed window shape."""
        with self._lock:
            f = self._goodput.observe(max(0.0, min(1.0, float(fraction))))
            if not f:
                return []
            if dominating:
                f["category"] = dominating
            return [self._flag(f)]

    def observe_fleet(self, per_rank: Dict[Any, dict]) -> List[dict]:
        """One fleet aggregation window: ``per_rank`` maps rank to a
        breakdown entry carrying ``win_step_time`` (the fleet
        aggregator's per-push windowed mean step time).  Flags when the
        same rank stays the slowest — and meaningfully slower than the
        fleet mean — for N consecutive windows."""
        times = {int(r): e["win_step_time"] for r, e in per_rank.items()
                 if isinstance(e, dict)
                 and isinstance(e.get("win_step_time"), (int, float))}
        with self._lock:
            if len(times) < 2:
                self._straggler_run = 0
                self._straggler_rank = None
                return []
            worst = max(times, key=lambda r: times[r])
            mean = sum(times.values()) / len(times)
            others = [t for r, t in times.items() if r != worst]
            peer_mean = sum(others) / len(others)
            charged = peer_mean > 0 and \
                times[worst] > peer_mean * self._straggler_ratio
            if not charged:
                self._straggler_run = 0
                self._straggler_rank = None
                self._straggler_active = False
                return []
            if worst == self._straggler_rank:
                self._straggler_run += 1
            else:
                self._straggler_rank = worst
                self._straggler_run = 1
                self._straggler_active = False
            if self._straggler_active or \
                    self._straggler_run < self._straggler_windows:
                return []
            self._straggler_active = True
            return [self._flag({
                "kind": "persistent_straggler", "rank": worst,
                "win_step_time": round(times[worst], 6),
                "fleet_mean": round(mean, 6),
                "windows": self._straggler_run})]

    def observe_serving(self, doc: dict) -> List[dict]:
        """One closed serving ``LatencyWindow`` doc (carrying the
        request ledger's stage shares, docs/OBSERVABILITY.md "Serving
        request ledger"): runs the ``ttft_drift`` / ``queue_growth`` /
        ``kv_thrash`` detectors and returns any NEW findings."""
        out = []
        with self._lock:
            ttft = doc.get("ttft_p50_s")
            if ttft is not None and doc.get("requests"):
                f = self._ttft.observe(float(ttft))
                if f:
                    if doc.get("worst_trace"):
                        f["worst_trace"] = doc["worst_trace"]
                    out.append(self._flag(f))
            for det in (self._queue_share, self._kv_share):
                f = det.observe(doc)
                if f:
                    out.append(self._flag(f))
        return out

    # -- reporting -----------------------------------------------------------
    def report(self, kind: str, **fields: Any) -> dict:
        """Public finding seam for detectors that live OUTSIDE this
        engine's own step/fleet feeds — the ``recompile_storm`` watcher
        (:mod:`horovod_tpu.profiling.compile_watch`) and the
        ``hbm_growth`` sampler (:mod:`horovod_tpu.profiling.memory`).
        The finding takes the exact same path as a native one: counter,
        flight event, bounded findings list, and (via the profiling
        hook) a possible triggered device-trace capture."""
        with self._lock:
            return self._flag({"kind": kind, **fields})

    def _flag(self, finding: dict, **extra: Any) -> dict:
        finding.update(extra)
        finding["ts"] = round(time.time(), 3)
        try:
            # causal tracing (docs/OBSERVABILITY.md "Causal tracing"):
            # the finding ROOTS a trace that the autopilot decision,
            # the action/ KV doc, the driver's handling, and the
            # resulting re-mesh episode all continue — one id from
            # detection to the first healthy step of the cure
            from horovod_tpu import tracing
            supplied = finding.get(tracing.TRACEPARENT)
            if supplied:
                # the caller is ALREADY inside a trace (a rollout
                # controller reporting its verdict): the finding
                # CONTINUES that trace as a child span instead of
                # rooting a new one — one id from the operation that
                # detected trouble through the autopilot's cure
                ctx = tracing.child(tracing.decode(supplied), "anomaly")
            else:
                ctx = tracing.new_trace("anomaly")
            if ctx is not None:
                finding.update(ctx.fields())
                finding[tracing.TRACEPARENT] = ctx.traceparent
        except Exception:
            pass
        self.findings.append(finding)
        del self.findings[:-MAX_FINDINGS]
        kind = finding["kind"]
        try:
            self._reg.counter(
                "hvd_anomaly_total",
                help="anomaly-engine findings, per detector kind",
                labels={"kind": kind}).inc()
        except Exception:
            pass
        try:
            # deep-profiling hook (docs/OBSERVABILITY.md "Deep
            # profiling"): a finding may arm a bounded device-trace
            # capture of the next steps; the planned path is stamped
            # into THIS finding dict before the flight event records
            # it, so every channel points at the same trace
            from horovod_tpu.profiling import on_anomaly
            on_anomaly(finding)
        except Exception:
            pass
        try:
            from horovod_tpu.diagnostics.flight_recorder import record_event
            # "detector", not "kind": the ring's own event-kind key wins
            # (same convention as the chaos seam's "fault" field)
            record_event("anomaly",
                         **{("detector" if k == "kind" else k): v
                            for k, v in finding.items()
                            if k not in ("ts", "traceparent")})
        except Exception:
            pass
        try:
            # autopilot seam (docs/OBSERVABILITY.md "Autopilot"): every
            # finding — native detectors and report_finding() externals
            # alike — is offered to the policy engine, which records a
            # decision (fired / dry-run / suppressed) per matching
            # policy; a cheap None check when HVD_TPU_AUTOPILOT=off
            from horovod_tpu.autopilot import on_finding
            on_finding(finding)
        except Exception:
            pass
        try:
            from horovod_tpu.common.logging import get_logger
            get_logger().warning("anomaly: %s %s", kind,
                                 {k: v for k, v in finding.items()
                                  if k not in ("kind", "ts")})
        except Exception:
            pass
        return finding

    def recent_findings(self, last_n: int = MAX_FINDINGS) -> List[dict]:
        with self._lock:
            return list(self.findings[-last_n:])

    def reset_baselines(self) -> None:
        """Forget the learned baselines but KEEP the findings: an
        elastic re-mesh legitimately changes step time (different world
        size) and must re-learn, while already-flagged degradation
        stays available to the autopsy."""
        alpha = self._step.baseline.alpha
        with self._lock:
            for det in (self._step, self._thr, self._exposed,
                        self._goodput, self._ttft):
                det.baseline = EwmaMad(alpha)
                det._streak = 0
                det._active = False
            for det in (self._queue_share, self._kv_share):
                det._streak = 0
                det._active = False
            self._straggler_rank = None
            self._straggler_run = 0
            self._straggler_active = False


_ENGINE: Optional[AnomalyEngine] = None
_ENGINE_LOCK = threading.Lock()


def default_engine() -> Optional[AnomalyEngine]:
    """The process-wide engine (None when ``HVD_TPU_ANOMALY=0``);
    created on first use, rebuilt by :func:`reset`."""
    global _ENGINE
    if not enabled():
        return None
    if _ENGINE is None:
        with _ENGINE_LOCK:
            if _ENGINE is None:
                _ENGINE = AnomalyEngine()
    return _ENGINE


def recent_findings() -> List[dict]:
    """Findings so far (empty when the engine never ran) — what the
    autopsy summary embeds under ``anomalies``."""
    eng = _ENGINE
    return eng.recent_findings() if eng is not None else []


def observe_serving_window(doc: dict) -> List[dict]:
    """Feed one closed serving window doc to the process-wide engine's
    serving detectors ([] when ``HVD_TPU_ANOMALY=0``)."""
    eng = default_engine()
    return eng.observe_serving(doc) if eng is not None else []


def report_finding(kind: str, **fields: Any) -> Optional[dict]:
    """Route an external detector's finding through the process-wide
    engine (None — silently dropped — when ``HVD_TPU_ANOMALY=0``)."""
    eng = default_engine()
    return eng.report(kind, **fields) if eng is not None else None


def reset() -> None:
    """Drop the process-wide engine so thresholds re-read env (tests,
    elastic re-init)."""
    global _ENGINE
    with _ENGINE_LOCK:
        _ENGINE = None


def reset_baselines() -> None:
    """Re-learn baselines in place (``hvd.init`` across an elastic
    re-mesh); no-op when the engine never ran."""
    eng = _ENGINE
    if eng is not None:
        eng.reset_baselines()
