"""Dependency-free metrics primitives: Counter / Gauge / Histogram + Registry.

The reference exposes its engine health only through the chrome-trace
timeline; the paper's observability story calls for first-class counters
(PAPER.md; reference gap noted in SURVEY.md). This module is the in-process
half: instruments record locally with a lock per instrument, ``snapshot()``
produces a plain-dict, **mergeable** view (sum counters/histograms across
workers; gauges merge by their declared aggregation), and
``render_prometheus()`` serializes a snapshot in Prometheus text exposition
format v0.0.4 for the per-worker HTTP exporter
(:mod:`horovod_tpu.metrics.exporter`).

Stdlib-only by design: the training hot path must not grow a pip
dependency for the sake of counters.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Default histogram buckets: fixed log-scale (powers of 2) from 1 ms to
# ~524 s — wide enough for step times from a pallas microbenchmark to a
# pathological straggler stall, cheap enough to merge across a pod.
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    1e-3 * 2.0 ** i for i in range(20))


def _label_key(labels: Optional[Dict[str, str]]) -> str:
    """Canonical label suffix, '' when unlabeled: ``{a="1",b="x"}``."""
    if not labels:
        return ""
    items = sorted((str(k), str(v)) for k, v in labels.items())
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in items)
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None) -> None:
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()

    def snapshot(self) -> dict:
        raise NotImplementedError


class Counter(_Instrument):
    """Monotonically increasing count (merge = sum)."""

    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> dict:
        return {"type": self.kind, "help": self.help, "value": self.value}


class Gauge(_Instrument):
    """Point-in-time value. ``agg`` declares how cross-worker merges
    combine samples: ``last`` (default), ``sum`` (e.g. throughput),
    ``max``, ``min`` (e.g. OOM margin: the tightest rank is THE
    number), or ``mean``."""

    kind = "gauge"

    def __init__(self, name, help="", labels=None,
                 agg: Optional[str] = None):
        super().__init__(name, help, labels)
        agg = agg or "last"
        if agg not in ("last", "sum", "max", "min", "mean"):
            raise ValueError(f"unknown gauge agg {agg!r}")
        self.agg = agg
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> dict:
        return {"type": self.kind, "help": self.help, "value": self.value,
                "agg": self.agg}


class Histogram(_Instrument):
    """Cumulative-bucket histogram over fixed (log-scale by default)
    bounds. Snapshots carry per-bucket counts + sum + count and merge by
    elementwise addition — bounds are part of the identity, so merging
    snapshots with different bounds is an error."""

    kind = "histogram"

    def __init__(self, name, help="", labels=None,
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, labels)
        bs = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        if any(b <= 0 or not math.isfinite(b) for b in bs):
            raise ValueError("bucket bounds must be finite and positive")
        self._bounds: Tuple[float, ...] = bs
        self._counts = [0] * (len(bs) + 1)  # last slot = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> dict:
        with self._lock:
            return {"type": self.kind, "help": self.help,
                    "bounds": list(self._bounds),
                    "counts": list(self._counts),
                    "sum": self._sum, "count": self._count}


class Registry:
    """Get-or-create instrument registry with mergeable snapshots.

    Keys are ``name`` + canonical label set; re-requesting an existing
    instrument returns the same object, requesting it with a different
    type raises (mirrors prometheus_client semantics without the dep).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}

    def _get_or_create(self, cls, name, help, labels, **kwargs):
        key = name + _label_key(labels)
        with self._lock:
            inst = self._instruments.get(key)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise ValueError(
                        f"metric {key!r} already registered as "
                        f"{inst.kind}, requested {cls.kind}")
                # explicitly requested options must match the existing
                # instrument — silently handing back different semantics
                # (a "sum" caller getting a "last" gauge) corrupts merges
                agg = kwargs.get("agg")
                if agg is not None and inst.agg != agg:
                    raise ValueError(
                        f"metric {key!r} already registered with "
                        f"agg={inst.agg!r}, requested {agg!r}")
                buckets = kwargs.get("buckets")
                if buckets is not None and \
                        tuple(sorted(buckets)) != inst._bounds:
                    raise ValueError(
                        f"metric {key!r} already registered with "
                        f"different bucket bounds")
                return inst
            inst = cls(name, help=help, labels=labels, **kwargs)
            self._instruments[key] = inst
            return inst

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None,
              agg: Optional[str] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels, agg=agg)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def get(self, name: str,
            labels: Optional[Dict[str, str]] = None) -> Optional[_Instrument]:
        """Fetch an existing instrument, or None — never creates (the
        get-or-create constructors would register a zero-valued
        instrument as a side effect of merely *asking*)."""
        with self._lock:
            return self._instruments.get(name + _label_key(labels))

    def unregister(self, name: str,
                   labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._instruments.pop(name + _label_key(labels), None)

    def drop_prefix(self, prefix: str) -> int:
        """Unregister every instrument whose key starts with ``prefix``;
        returns the count.  Used on (re-)init to drop gauges mirroring a
        DEAD engine's state (``hvd_engine_*``, ``hvd_straggler_*``) so a
        re-meshed world's scrape never serves the previous generation's
        last values as if they were live."""
        with self._lock:
            keys = [k for k in self._instruments if k.startswith(prefix)]
            for k in keys:
                del self._instruments[k]
            return len(keys)

    def clear(self) -> None:
        with self._lock:
            self._instruments.clear()

    def snapshot(self) -> Dict[str, dict]:
        """Plain-dict view: ``{key: {type, help, ...values}}``. Keys embed
        the label set (``name{rank="1"}``); values are merge-ready."""
        with self._lock:
            items = list(self._instruments.items())
        return {key: inst.snapshot() for key, inst in items}

    @staticmethod
    def merge(snapshots: Iterable[Dict[str, dict]]) -> Dict[str, dict]:
        """Combine per-worker snapshots: counters and histograms add,
        gauges combine per their ``agg`` declaration."""
        out: Dict[str, dict] = {}
        means: Dict[str, List[float]] = {}
        for snap in snapshots:
            for key, s in snap.items():
                if key not in out:
                    out[key] = {k: (list(v) if isinstance(v, list) else v)
                                for k, v in s.items()}
                    if s["type"] == "gauge" and s.get("agg") == "mean":
                        means[key] = [s["value"]]
                    continue
                t = out[key]
                if t["type"] != s["type"]:
                    raise ValueError(f"type mismatch merging {key!r}")
                if s["type"] == "counter":
                    t["value"] += s["value"]
                elif s["type"] == "histogram":
                    if t["bounds"] != s["bounds"]:
                        raise ValueError(
                            f"bucket bounds mismatch merging {key!r}")
                    t["counts"] = [a + b for a, b in
                                   zip(t["counts"], s["counts"])]
                    t["sum"] += s["sum"]
                    t["count"] += s["count"]
                else:  # gauge
                    agg = s.get("agg", "last")
                    if agg == "sum":
                        t["value"] += s["value"]
                    elif agg == "max":
                        t["value"] = max(t["value"], s["value"])
                    elif agg == "min":
                        t["value"] = min(t["value"], s["value"])
                    elif agg == "mean":
                        means.setdefault(key, [t["value"]]).append(
                            s["value"])
                    else:  # last
                        t["value"] = s["value"]
        for key, vals in means.items():
            out[key]["value"] = sum(vals) / len(vals)
        return out


def render_prometheus(snapshot: Dict[str, dict]) -> str:
    """Serialize a snapshot as Prometheus text format v0.0.4."""
    # group by bare metric name so HELP/TYPE are emitted once per family
    families: Dict[str, List[Tuple[str, dict]]] = {}
    for key in sorted(snapshot):
        name = key.split("{", 1)[0]
        families.setdefault(name, []).append((key, snapshot[key]))
    lines: List[str] = []
    for name, series in families.items():
        first = series[0][1]
        if first.get("help"):
            lines.append(f"# HELP {name} {first['help']}")
        lines.append(f"# TYPE {name} {first['type']}")
        for key, s in series:
            label_part = key[len(name):]  # "" or '{a="b"}'
            if s["type"] == "histogram":
                inner = label_part[1:-1] if label_part else ""
                cum = 0
                for bound, c in zip(s["bounds"], s["counts"]):
                    cum += c
                    le = _fmt(bound)
                    sep = "," if inner else ""
                    lines.append(
                        f'{name}_bucket{{{inner}{sep}le="{le}"}} {cum}')
                cum += s["counts"][-1]
                sep = "," if inner else ""
                lines.append(
                    f'{name}_bucket{{{inner}{sep}le="+Inf"}} {cum}')
                lines.append(f"{name}_sum{label_part} {_fmt(s['sum'])}")
                lines.append(f"{name}_count{label_part} {s['count']}")
            else:
                lines.append(f"{name}{label_part} {_fmt(s['value'])}")
    return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


_DEFAULT = Registry()


def default_registry() -> Registry:
    """The process-wide registry scraped by the worker exporter."""
    return _DEFAULT
