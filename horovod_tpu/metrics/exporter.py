"""Per-worker HTTP metrics exporter: ``/metrics`` + ``/healthz``.

Prometheus-compatible scrape endpoint over the same threaded HTTP server
machinery as the rendezvous KV plane (:mod:`horovod_tpu.runner.http_kv`).
One exporter per worker process; on a multi-worker host each worker binds
``HVD_TPU_METRICS_PORT + local_rank`` so a pod-wide scrape config is just
``host:base_port+i`` (reference analog: none — the reference's only
runtime introspection is the timeline file).

Collectors registered with the exporter run at scrape time (pull model):
each is a zero-arg callable that refreshes gauges in the registry before
rendering. A failing collector is logged and skipped — scrapes must never
take down training.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler
from typing import Callable, Iterable, Optional

from horovod_tpu.common.logging import get_logger
from horovod_tpu.metrics.registry import (Registry, default_registry,
                                          render_prometheus)
from horovod_tpu.runner.http_kv import ThreadedHTTPServer

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def peer_endpoint(rank: int, base_port: int,
                  hosts: Optional[list] = None) -> tuple:
    """(host, exporter port) for ``rank`` under the exporter contract:
    port is ``base + local rank`` (the rank's index among the ranks
    sharing its host), host from a rank-indexed ``HVD_TPU_PEER_HOSTS``
    list.  THE one implementation of the peer-address derivation — the
    autopsy's cross-rank evidence fetch and the fleet tree's upstream
    push both route through it, so the addressing contract cannot
    silently fork.  A rank beyond (or blank in) the host map falls back
    to the no-map convention (loopback, base + global rank) instead of
    raising — a short map must degrade, not kill the caller's loop."""
    if hosts and rank < len(hosts) and hosts[rank]:
        host = hosts[rank]
        local = sum(1 for q in range(rank)
                    if q < len(hosts) and hosts[q] == host)
        return host, base_port + local
    return "127.0.0.1", base_port + rank


class _MetricsHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # silence per-scrape access lines
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        raw_path, _, query = self.path.partition("?")
        path = raw_path.rstrip("/") or "/"
        exporter: "MetricsExporter" = self.server.exporter
        if path in ("/metrics", "/"):
            body = exporter.render().encode()
            self._send(200, body, CONTENT_TYPE)
        elif path == "/metrics/fleet":
            # tree-aggregated whole-job view (docs/OBSERVABILITY.md
            # "Fleet view"): rank 0 serves the full fleet; any other
            # rank serves its subtree (useful for debugging a branch)
            fleet = exporter.fleet
            if fleet is None:
                self._send(404, b"fleet aggregation not enabled\n",
                           "text/plain")
                return
            try:
                body = fleet.render_fleet().encode()
            except Exception as e:
                self._send(500, repr(e).encode() + b"\n", "text/plain")
                return
            self._send(200, body, CONTENT_TYPE)
        elif path == "/healthz":
            doc = exporter.health()
            code = 200 if doc.get("status") == "ok" else 503
            self._send(code, json.dumps(doc).encode(), "application/json")
        elif path == "/readyz":
            # READINESS, split from /healthz LIVENESS (docs/SERVING.md):
            # healthz answers "is the process alive and making progress"
            # (restart me when not); readyz answers "should you route
            # traffic/work at me right now" (a draining or still-
            # restoring replica is alive but NOT ready) — orchestrators
            # that conflate the two discover drain via errors
            doc = exporter.ready()
            code = 200 if doc.get("ready") else 503
            self._send(code, json.dumps(doc).encode(), "application/json")
        elif path.startswith("/debug/"):
            self._debug(path[len("/debug/"):], query)
        else:
            self._send(404, b"not found\n", "text/plain")

    def do_POST(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        exporter: "MetricsExporter" = self.server.exporter
        if path != "/metrics/push":
            self._send(404, b"not found\n", "text/plain")
            return
        fleet = exporter.fleet
        if fleet is None:
            self._send(404, b"fleet aggregation not enabled\n",
                       "text/plain")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(length))
            accepted = fleet.ingest(doc)
        except Exception as e:  # a malformed push must not kill serving
            self._send(400, repr(e).encode() + b"\n", "text/plain")
            return
        if accepted:
            self._send(200, b"ok\n", "text/plain")
        else:
            # 409: sender is from another world/generation — tells an
            # elastic straggler to stop pushing here
            self._send(409, b"rejected (world/generation mismatch)\n",
                       "text/plain")

    def _debug(self, kind: str, query: str = "") -> None:
        """Hang-autopsy evidence + deep-profiling endpoints
        (docs/OBSERVABILITY.md "Flight recorder & hang autopsy" /
        "Deep profiling"): rank 0's watchdog scrapes every peer's
        ``/debug/stacks`` / ``/debug/flight`` / ``/debug/engine`` so
        one directory answers "which rank is stuck in what", and
        ``/debug/profile?steps=N`` arms a bounded device-trace capture
        of the next N steps (``&peers=1`` fans the request out to every
        peer exporter via the ``HVD_TPU_PEER_HOSTS`` map).  Served from
        the exporter's own thread pool, so they answer even while the
        training thread is wedged."""
        try:
            if kind == "profile":
                self._send(200,
                           json.dumps(_arm_profile(query),
                                      default=str).encode(),
                           "application/json")
            elif kind == "stacks":
                from horovod_tpu.diagnostics.autopsy import stacks_text
                self._send(200, stacks_text().encode(), "text/plain")
            elif kind == "flight":
                from horovod_tpu.diagnostics.flight_recorder import recorder
                self._send(200,
                           json.dumps(recorder().dump(),
                                      default=str).encode(),
                           "application/json")
            elif kind == "engine":
                from horovod_tpu.diagnostics.autopsy import engine_doc
                self._send(200,
                           json.dumps(engine_doc(), default=str).encode(),
                           "application/json")
            elif kind == "exemplars":
                # the serving ledger's tail exemplars: worst requests
                # per window with trace id + full stage breakdown
                # (docs/OBSERVABILITY.md "Serving request ledger")
                from horovod_tpu.serving.ledger import exemplars
                self._send(200,
                           json.dumps({"exemplars": exemplars()},
                                      default=str).encode(),
                           "application/json")
            else:
                self._send(404, b"unknown debug endpoint\n", "text/plain")
        except Exception as e:  # evidence collection must never crash
            self._send(500, repr(e).encode() + b"\n", "text/plain")


def _arm_profile(query: str) -> dict:
    """``/debug/profile`` body: arm a capture on THIS rank (and, with
    ``peers=1``, on every peer reachable through the autopsy's
    ``HVD_TPU_PEER_HOSTS`` addressing).  The capture starts at the next
    step boundary; the response carries the planned trace path (or
    ``started: false`` when a capture is already pending/active)."""
    from urllib.parse import parse_qs
    from urllib.request import urlopen

    from horovod_tpu.profiling import default_manager
    params = parse_qs(query)

    def _int(name, default):
        try:
            return int(params[name][0])
        except (KeyError, IndexError, ValueError):
            return default

    steps = _int("steps", 0) or None
    info = default_manager().request_capture(steps=steps,
                                             reason="debug_endpoint")
    doc = {"rank": _best_effort_rank(), "started": info is not None}
    if info is not None:
        doc["path"] = info["path"]
        doc["steps"] = info["steps"]
    else:
        doc["status"] = default_manager().status()
    if _int("peers", 0):
        from horovod_tpu.diagnostics.autopsy import peer_debug_ports
        peers = {}
        steps_q = f"?steps={steps}" if steps else ""
        for r, (host, port) in sorted(peer_debug_ports().items()):
            url = f"http://{host}:{port}/debug/profile{steps_q}"
            try:
                body = urlopen(url, timeout=5.0).read()
                peers[str(r)] = json.loads(body)
            except Exception as e:  # best-effort fan-out
                peers[str(r)] = {"error": repr(e)}
        doc["peers"] = peers
    return doc


def _best_effort_rank() -> int:
    from horovod_tpu.diagnostics.flight_recorder import (
        _best_effort_rank as _rank)
    return _rank()


class MetricsExporter:
    """Threaded scrape server for one worker process.

    Args:
      registry: registry to render (default: the process-wide one).
      port: TCP port; 0 binds an ephemeral port (tests).
      collectors: callables run before each render to refresh derived
        gauges (e.g. :class:`horovod_tpu.metrics.engine.EngineCollector`).
      health_fn: optional callable returning the ``/healthz`` JSON doc;
        default reports ``{"status": "ok"}``.
      ready_fn: optional callable returning the ``/readyz`` JSON doc
        (must carry a boolean ``ready``); default derives readiness
        from ``health_fn`` (ready iff healthy).  Custom embedders
        install their own probe here; serving replicas implement the
        SAME /readyz contract (model loaded + queue under budget + not
        draining) on their own request server, since their HTTP plane
        also carries /infer (:mod:`horovod_tpu.serving.replica`).
    """

    def __init__(self, registry: Optional[Registry] = None, port: int = 0,
                 collectors: Iterable[Callable[[], None]] = (),
                 health_fn: Optional[Callable[[], dict]] = None,
                 ready_fn: Optional[Callable[[], dict]] = None) -> None:
        self._registry = registry or default_registry()
        self._collectors = list(collectors)
        self._health_fn = health_fn
        self._ready_fn = ready_fn
        self._httpd = ThreadedHTTPServer(("0.0.0.0", port), _MetricsHandler)
        self._httpd.exporter = self
        self._thread: Optional[threading.Thread] = None
        # fleet fan-in node served/fed through this exporter's HTTP
        # plane (/metrics/fleet, /metrics/push); owned: stop() stops it
        self.fleet = None  # metrics.fleet.FleetAggregator

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def add_collector(self, fn: Callable[[], None]) -> None:
        self._collectors.append(fn)

    def render(self) -> str:
        for fn in self._collectors:
            try:
                fn()
            except Exception as e:  # scrapes must never crash training
                get_logger().debug("metrics collector %r failed: %r", fn, e)
        return render_prometheus(self._registry.snapshot())

    def health(self) -> dict:
        if self._health_fn is not None:
            try:
                return self._health_fn()
            except Exception as e:
                return {"status": "error", "error": repr(e)}
        return {"status": "ok"}

    def set_ready_fn(self, fn: Optional[Callable[[], dict]]) -> None:
        """Install (or clear) the readiness probe after construction —
        a replica whose model loads asynchronously registers it once
        the serving loop owns the state the probe reads."""
        self._ready_fn = fn

    def ready(self) -> dict:
        """The ``/readyz`` doc.  A failing probe reads as NOT ready
        (fail-closed: an orchestrator must not route at a replica whose
        own readiness probe is broken), unlike ``health()`` where a
        failing probe still reports the process alive-ish."""
        if self._ready_fn is not None:
            try:
                doc = self._ready_fn()
                doc.setdefault("ready", False)
                return doc
            except Exception as e:
                return {"ready": False, "error": repr(e)}
        h = self.health()
        return {"ready": h.get("status") == "ok", "health": h}

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-tpu-metrics",
            daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        # the tree node first: its push loop targets peers that are
        # also shutting down, and it must not outlive its own registry
        if self.fleet is not None:
            try:
                self.fleet.stop()
            except Exception:
                pass
            self.fleet = None
        # shutdown() handshakes with serve_forever() and blocks forever if
        # the serving thread was never started — only call it after start()
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._httpd.server_close()


def start_worker_exporter(state) -> Optional[MetricsExporter]:
    """Start the per-worker exporter for an initialized ``_GlobalState``
    when ``HVD_TPU_METRICS_PORT`` is set (>0). Called from ``hvd.init``;
    never raises — a port squat degrades to a warning, not a failed init.
    """
    cfg = state.config
    base = getattr(cfg, "metrics_port", 0)
    if not base or base <= 0:
        return None
    port = base + max(state.local_rank, 0)
    from horovod_tpu.metrics.engine import EngineCollector

    def counters_fn():
        be = state.backend
        return be.counters() if be is not None else {}

    def stragglers_fn():
        fn = getattr(state.backend, "stragglers", None)
        return fn() if fn is not None else {}

    def health():
        """Liveness, not just process-up (docs/OBSERVABILITY.md): last
        step age + watchdog state + engine reachability, going 503
        (``status != ok``) once the step age crosses the watchdog
        threshold — an external orchestrator can act on the stall
        BEFORE the in-process autopsy fires."""
        doc = {"status": "ok" if state.initialized else "shutdown",
               "rank": state.rank, "size": state.size,
               "hostname": state.hostname}
        from horovod_tpu.diagnostics import watchdog as _wd
        live = _wd.liveness()
        age = live.get("last_step_age_s")
        doc["watchdog"] = {"armed": live["armed"],
                           "timeout_s": live["timeout_s"],
                           "last_fed_age_s": age}
        doc["last_step"] = live.get("last_step")
        doc["last_step_age_s"] = age
        be = state.backend
        engine_alive = None
        if be is not None:
            try:
                be.counters()
                engine_alive = True
            except Exception:
                engine_alive = False
        doc["engine_alive"] = engine_alive
        threshold = live["timeout_s"]
        if doc["status"] == "ok" and threshold and threshold > 0 \
                and age is not None and age > threshold:
            # steps HAVE been flowing (age is only set after the first
            # progress stamp) and then stopped past the hang threshold
            doc["status"] = "stalled"
        return doc

    registry = default_registry()
    # a re-meshed world must not serve the dead engine's last values as
    # live state: the mirror gauges are re-populated by the NEW
    # collector on first scrape (cumulative counters like
    # hvd_stall_warnings_total are a different prefix and survive)
    for prefix in ("hvd_engine_", "hvd_straggler_"):
        registry.drop_prefix(prefix)
    # the engine's autotune DECISION mirrors (docs/OBSERVABILITY.md
    # "Autotune metrics") die with the engine too — but only these four
    # exact names: the mesh tuner's hvd_autotune_plan_*/locked/... share
    # the namespace and must survive a re-mesh (the plan cache is what
    # makes the re-meshed world start tuned)
    for name in ("hvd_autotune_fusion_bytes", "hvd_autotune_cycle_ms",
                 "hvd_autotune_hierarchical",
                 "hvd_autotune_cache_enabled"):
        registry.drop_prefix(name)
    collector = EngineCollector(counters_fn, registry=registry,
                                stragglers_fn=stragglers_fn)
    try:
        exp = MetricsExporter(registry=registry, port=port,
                              collectors=[collector.collect],
                              health_fn=health)
        exp.start()
    except (OSError, OverflowError) as e:  # squat or base+local_rank > 65535
        get_logger().warning(
            "metrics exporter could not bind port %d (%s); metrics "
            "disabled for this worker", port, e)
        return None
    # fleet fan-in tree node (docs/OBSERVABILITY.md "Fleet view"):
    # child pushes ride this exporter plane, rank 0 serves
    # /metrics/fleet; rebuilt per init so an elastic re-mesh re-wires
    # the tree from the NEW (rank, size)
    from horovod_tpu.metrics.fleet import FleetAggregator, fleet_enabled
    if fleet_enabled() and state.rank >= 0:
        import os as _os
        gen = 0
        try:
            gen = int(_os.environ.get("HVD_ELASTIC_GENERATION", "0"))
        except ValueError:
            pass
        exp.fleet = FleetAggregator(
            rank=state.rank, size=state.size, base_port=base,
            registry=registry, collectors=[collector.collect],
            generation=gen, cross_size=state.cross_size).start()
    get_logger().info("metrics exporter serving on :%d/metrics", exp.port)
    return exp
