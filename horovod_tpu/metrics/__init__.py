"""Unified metrics & telemetry subsystem.

Seven layers (see ``docs/OBSERVABILITY.md``):

* :mod:`~horovod_tpu.metrics.registry` — dependency-free Counter / Gauge /
  Histogram with mergeable snapshots and Prometheus text rendering.
* :mod:`~horovod_tpu.metrics.engine` — derived view over the C++ engine's
  control-plane counters (cache-hit rate, fusion efficiency, bytes/s) and
  the coordinator's straggler attribution.
* :mod:`~horovod_tpu.metrics.exporter` — per-worker HTTP ``/metrics`` +
  ``/healthz`` endpoints, enabled by ``HVD_TPU_METRICS_PORT``.
* :mod:`~horovod_tpu.metrics.fleet` — tree-aggregated whole-job view:
  ranks push mergeable snapshots up a fan-in tree; rank 0 serves one
  ``/metrics/fleet`` scrape with per-rank breakdown gauges.
* :mod:`~horovod_tpu.metrics.timeseries` — step-aligned history: bounded
  ring + ``HVD_TPU_OBS_DIR`` JSONL, queryable by
  ``python -m horovod_tpu.metrics history``.
* :mod:`~horovod_tpu.metrics.anomaly` — online EWMA+MAD detectors over
  the series: step-time drift, throughput regression, persistent
  straggler, exposed-comm growth.
* :mod:`~horovod_tpu.metrics.mfu` — chip peak FLOPs + compiled-HLO FLOPs
  counting shared by ``bench.py`` and the train-loop telemetry.
"""

from horovod_tpu.metrics.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    DEFAULT_BUCKETS,
    default_registry,
    render_prometheus,
)
from horovod_tpu.metrics.engine import (  # noqa: F401
    EngineCollector,
    derived_ratios,
)
from horovod_tpu.metrics.exporter import (  # noqa: F401
    MetricsExporter,
    start_worker_exporter,
)
from horovod_tpu.metrics.fleet import FleetAggregator  # noqa: F401
from horovod_tpu.metrics.timeseries import (  # noqa: F401
    StepSeriesRecorder,
    TimeSeriesRing,
    read_series,
)
from horovod_tpu.metrics.anomaly import AnomalyEngine  # noqa: F401
