"""Unified metrics & telemetry subsystem.

Four layers (see ``docs/OBSERVABILITY.md``):

* :mod:`~horovod_tpu.metrics.registry` — dependency-free Counter / Gauge /
  Histogram with mergeable snapshots and Prometheus text rendering.
* :mod:`~horovod_tpu.metrics.engine` — derived view over the C++ engine's
  control-plane counters (cache-hit rate, fusion efficiency, bytes/s) and
  the coordinator's straggler attribution.
* :mod:`~horovod_tpu.metrics.exporter` — per-worker HTTP ``/metrics`` +
  ``/healthz`` endpoints, enabled by ``HVD_TPU_METRICS_PORT``.
* :mod:`~horovod_tpu.metrics.mfu` — chip peak FLOPs + compiled-HLO FLOPs
  counting shared by ``bench.py`` and the train-loop telemetry.
"""

from horovod_tpu.metrics.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    DEFAULT_BUCKETS,
    default_registry,
    render_prometheus,
)
from horovod_tpu.metrics.engine import (  # noqa: F401
    EngineCollector,
    derived_ratios,
)
from horovod_tpu.metrics.exporter import (  # noqa: F401
    MetricsExporter,
    start_worker_exporter,
)
