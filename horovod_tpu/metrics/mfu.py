"""MFU accounting helpers: chip peak FLOPs + compiled-HLO FLOPs counting.

Shared between the benchmark harness (``bench.py``) and the train-loop
telemetry (:class:`horovod_tpu.train.callbacks.TelemetryCallback`), so the
two report the same MFU for the same program (MLPerf TPU-pod scaling work
emphasizes step-time/MFU accounting as the scaling metric — PAPERS.md,
arXiv:1909.09756).
"""

from __future__ import annotations

from typing import Optional

# Peak dense bf16 FLOPs per chip by device-kind substring (public specs).
PEAK_BF16_FLOPS = (
    ("v5 lite", 197e12), ("v5litepod", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v6", 918e12), ("v4", 275e12), ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops(device_kind: str) -> Optional[float]:
    """Peak dense bf16 FLOPs/s for a device-kind string, or None when the
    chip is unknown (CPU hosts, future TPUs not yet tabled)."""
    kind = device_kind.lower()
    for sub, peak in PEAK_BF16_FLOPS:
        if sub in kind:
            return peak
    return None


def device_peak_flops() -> Optional[float]:
    """Peak FLOPs of the first local device (None off-TPU)."""
    import jax
    devs = jax.devices()
    return peak_flops(devs[0].device_kind) if devs else None


def hlo_flops_per_device(jitted, args, factor: int = 1) -> Optional[float]:
    """Per-device FLOPs of one dispatch of ``jitted(*args)`` from the
    compiled executable's ``cost_analysis()`` (post-SPMD, so per-device by
    construction). ``factor`` scales for in-graph multi-step: XLA counts a
    while-loop (``lax.scan``) body ONCE, not trip-count times. Returns
    None when cost analysis is unavailable (caller falls back to an
    analytic estimate)."""
    try:
        cost = jitted.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return (float(cost.get("flops", 0.0)) * factor) or None
    except Exception:
        return None


def mfu(flops_per_device_per_step: float, step_seconds: float,
        peak: Optional[float] = None) -> Optional[float]:
    """Model FLOPs utilization for one step; None when the peak is
    unknown or inputs are degenerate."""
    if peak is None:
        peak = device_peak_flops()
    if not peak or not flops_per_device_per_step or step_seconds <= 0:
        return None
    return flops_per_device_per_step / step_seconds / peak
