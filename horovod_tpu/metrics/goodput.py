"""Goodput ledger: closed-books wall-clock attribution (ISSUE 16).

ROADMAP item 5's gap in one sentence: MFU is 0.31 and the other 69% of
wall time is spread across five observability planes nobody joins.
This module is the join — a per-rank ledger that attributes EVERY
second of job wall time to a closed category set:

* ``compute``          — in-step time not claimed by any cost below;
* ``exposed_comm``     — collective time the overlap schedule failed
                         to hide (``hvd_overlap_exposed_comm_seconds``);
* ``compile``          — XLA backend compiles (compile_watch), whether
                         they landed inside a step (first dispatch) or
                         between steps (AOT warmup);
* ``remesh_recovery``  — elastic re-mesh episodes (``elastic/remesh``);
* ``checkpoint_stall`` — the train-thread-blocking slice of the
                         checkpoint store: the inline device→host
                         snapshot, a ``wait()``-ed save, a restore;
* ``input_wait``       — inter-step gaps not explained by any of the
                         above: the host loop waiting on data;
* ``guard_skipped``    — steps the numeric guardrail threw away
                         (``hvd_guard_skipped_steps_total``): wall time
                         spent computing an update that was zeroed;
* ``idle_other``       — the residual.  Books must close: the residual
                         is itself a reported category, never silently
                         dropped, and a window whose categories fail to
                         sum to wall time within
                         ``HVD_TPU_GOODPUT_TOLERANCE`` is flagged
                         loudly (flight event + warning), never
                         papered over.

Everything is fed from seams that already exist — the StepTimer step
envelope, the overlap gauges, compile_watch totals, re-mesh
``Episode`` totals, the checkpoint store's inline timings, guard-skip
counters — no new instrumentation on the hot path.  The ledger closes
a window every ``HVD_TPU_GOODPUT_WINDOW`` completed steps and emits
each closed window four ways:

* ``hvd_goodput_seconds_total{category=...}`` counters (fleet-merged
  by summation through the fan-in tree);
* the ``hvd_goodput_fraction`` gauge — the productive (compute)
  fraction of the window, ``agg="mean"`` across ranks;
* a ``goodput_window`` flight-recorder event (the double-entry stamp);
* one ``{"goodput": ...}`` point in the step time-series store
  (rendered by ``python -m horovod_tpu.metrics history --goodput``).

The anomaly engine's ``goodput_regression`` detector observes the
productive fraction per window; a sustained drop flags a finding
naming the dominating non-compute category, which the anomaly→profile
hook turns into a device-trace capture of the regression itself.

``HVD_TPU_GOODPUT=0`` disables the whole plane at near-zero cost.
Every emission path is exception-proofed: accounting must never break
training.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from horovod_tpu.common.config import env_bool, env_float, env_int

CATEGORIES = ("compute", "exposed_comm", "compile", "remesh_recovery",
              "checkpoint_stall", "input_wait", "guard_skipped",
              "idle_other")

_LOCK = threading.Lock()
_LEDGER: Optional["GoodputLedger"] = None


def _compile_seconds_total() -> float:
    try:
        from horovod_tpu.profiling import compile_watch
        return float(compile_watch.totals().get("seconds_total", 0.0))
    except Exception:
        return 0.0


class GoodputLedger:
    """Per-rank wall-clock accountant over fixed step windows.

    The clock runs from the FIRST ``note_step_begin`` (setup before the
    loop is the bench's business, not the steady-state ledger's); from
    then on every perf_counter second between window open and window
    close lands in exactly one category.
    """

    def __init__(self, window_steps: Optional[int] = None,
                 tolerance: Optional[float] = None) -> None:
        self.window_steps = max(1, int(
            window_steps if window_steps is not None
            else env_int("GOODPUT_WINDOW", 50)))
        self.tolerance = float(
            tolerance if tolerance is not None
            else env_float("GOODPUT_TOLERANCE", 0.01))
        self._lock = threading.Lock()
        # cumulative closed-window totals (seconds per category)
        self.totals: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self.wall_total = 0.0
        self.steps_total = 0
        self.windows_closed = 0
        self.books_violations = 0
        self.max_residual_frac = 0.0
        self.recent: deque = deque(maxlen=32)  # closed window records
        self._reset_window()

    # -- window state ---------------------------------------------------
    def _reset_window(self) -> None:
        self._t_open: Optional[float] = None
        self._steps = 0
        self._in_step = 0.0
        self._exposed = 0.0
        self._guard = 0.0
        self._gap = 0.0
        self._ckpt = 0.0
        self._remesh = 0.0
        self._compile0 = 0.0
        self._guard_count0: Optional[float] = None
        self._last_end: Optional[float] = None
        self._step_open = False

    def _open_window(self, now: float) -> None:
        self._reset_window()
        self._t_open = now
        self._compile0 = _compile_seconds_total()

    # -- feeds (all cheap; all exception-proofed by the module seams) ---
    def note_step_begin(self) -> None:
        now = time.perf_counter()
        with self._lock:
            if self._t_open is None:
                self._open_window(now)
            elif self._last_end is not None:
                self._gap += max(0.0, now - self._last_end)
            self._step_open = True
            self._guard_count0 = self._read_guard_count()

    def note_step_end(self, dt: float) -> None:
        now = time.perf_counter()
        with self._lock:
            if self._t_open is None or not self._step_open:
                return
            self._step_open = False
            dt = max(0.0, float(dt))
            self._in_step += dt
            exposed = self._read_exposed()
            if exposed is not None:
                self._exposed += min(max(0.0, exposed), dt)
            guard_now = self._read_guard_count()
            if (guard_now is not None and self._guard_count0 is not None
                    and guard_now > self._guard_count0):
                # the whole step was spent on an update the guard zeroed
                self._guard += dt
            self._last_end = now
            self._steps += 1
            if self._steps >= self.window_steps:
                self._close_window_locked(now)

    def note_checkpoint_stall(self, seconds: float) -> None:
        """Train-thread seconds blocked on the checkpoint store (inline
        snapshot, waited save, restore)."""
        with self._lock:
            if self._t_open is not None:
                self._ckpt += max(0.0, float(seconds))

    def note_remesh(self, seconds: float) -> None:
        """A completed elastic re-mesh episode's total recovery time."""
        with self._lock:
            if self._t_open is not None:
                self._remesh += max(0.0, float(seconds))

    def _read_exposed(self) -> Optional[float]:
        try:
            from horovod_tpu.metrics.registry import default_registry
            g = default_registry().get("hvd_overlap_exposed_comm_seconds")
            return float(g.value) if g is not None else None
        except Exception:
            return None

    def _read_guard_count(self) -> Optional[float]:
        try:
            from horovod_tpu.metrics.registry import default_registry
            c = default_registry().get("hvd_guard_skipped_steps_total")
            return float(c.value) if c is not None else None
        except Exception:
            return None

    # -- closing the books ----------------------------------------------
    def flush(self) -> Optional[Dict[str, Any]]:
        """Close the current window early (autopsy / end-of-run / bench:
        the partial window's evidence matters more than cadence).
        Returns the closed record, or None if no step has landed."""
        with self._lock:
            if self._t_open is None or self._steps == 0:
                return None
            return self._close_window_locked(time.perf_counter())

    def _close_window_locked(self, now: float) -> Dict[str, Any]:
        wall = max(0.0, now - self._t_open)
        compile_delta = max(
            0.0, _compile_seconds_total() - self._compile0)
        # Sequential clamping: each claimed cost is capped by the time
        # actually left to claim, so the categories sum to wall time by
        # construction — the tolerance only has to absorb float error.
        in_step = min(self._in_step, wall)
        guard = min(self._guard, in_step)
        rest = in_step - guard
        exposed = min(self._exposed, rest)
        rest -= exposed
        compile_in = min(compile_delta, rest)
        compute = rest - compile_in
        compile_out = compile_delta - compile_in
        out_step = wall - in_step
        ckpt = min(self._ckpt, out_step)
        rem = out_step - ckpt
        remesh = min(self._remesh, rem)
        rem -= remesh
        co = min(compile_out, rem)
        rem -= co
        input_wait = min(
            max(0.0, self._gap - ckpt - remesh - co), rem)
        rem -= input_wait
        idle_other = max(0.0, rem)
        cats = {
            "compute": compute,
            "exposed_comm": exposed,
            "compile": compile_in + co,
            "remesh_recovery": remesh,
            "checkpoint_stall": ckpt,
            "input_wait": input_wait,
            "guard_skipped": guard,
            "idle_other": idle_other,
        }
        residual = wall - sum(cats.values())
        residual_frac = abs(residual) / wall if wall > 0 else 0.0
        closed = residual_frac <= self.tolerance
        fraction = compute / wall if wall > 0 else 0.0
        record = {
            "wall_s": wall,
            "steps": self._steps,
            "seconds": cats,
            "fractions": {c: (v / wall if wall > 0 else 0.0)
                          for c, v in cats.items()},
            "fraction": fraction,
            "residual_s": residual,
            "closed": closed,
        }
        self.wall_total += wall
        self.steps_total += self._steps
        for c, v in cats.items():
            self.totals[c] += v
        self.windows_closed += 1
        self.max_residual_frac = max(self.max_residual_frac,
                                     residual_frac)
        if not closed:
            self.books_violations += 1
        self.recent.append(record)
        # window state rolls over; the clock keeps running so the gap
        # between windows is itself accounted (next window opens NOW)
        self._open_window(now)
        self._emit(record)
        return record

    @staticmethod
    def dominating(record: Dict[str, Any]) -> Optional[str]:
        """The non-compute category claiming the most wall time."""
        secs = record.get("seconds") or {}
        loss = {c: v for c, v in secs.items() if c != "compute"}
        if not loss:
            return None
        return max(loss, key=loss.get)

    def _emit(self, record: Dict[str, Any]) -> None:
        cats = record["seconds"]
        try:
            from horovod_tpu.metrics.registry import default_registry
            reg = default_registry()
            for c, v in cats.items():
                reg.counter(
                    "hvd_goodput_seconds_total",
                    help="wall seconds attributed per goodput category",
                    labels={"category": c}).inc(v)
            reg.gauge(
                "hvd_goodput_fraction",
                help="productive (compute) fraction of the last "
                     "goodput window", agg="mean").set(record["fraction"])
        except Exception:
            pass
        try:
            from horovod_tpu.diagnostics.flight_recorder import \
                record_event
            record_event(
                "goodput_window", wall_s=round(record["wall_s"], 4),
                steps=record["steps"],
                closed=record["closed"],
                residual_s=round(record["residual_s"], 6),
                **{f"{c}_s": round(v, 4) for c, v in cats.items()})
        except Exception:
            pass
        if not record["closed"]:
            try:
                from horovod_tpu.common.logging import get_logger
                get_logger().warning(
                    "goodput books did NOT close: window wall %.3fs vs "
                    "categories %.3fs (residual %.4fs > tolerance %.3f)",
                    record["wall_s"], sum(cats.values()),
                    record["residual_s"], self.tolerance)
            except Exception:
                pass
        try:
            from horovod_tpu.metrics import timeseries
            timeseries.record_point({
                "goodput": {c: round(v, 4) for c, v in cats.items()},
                "goodput_wall_s": round(record["wall_s"], 4),
                "goodput_fraction": round(record["fraction"], 4),
                "goodput_steps": record["steps"],
                "goodput_closed": record["closed"]})
        except Exception:
            pass
        try:
            from horovod_tpu.metrics.anomaly import default_engine
            eng = default_engine()
            if eng is not None:
                eng.observe_goodput(record["fraction"],
                                    dominating=self.dominating(record))
        except Exception:
            pass

    # -- views -----------------------------------------------------------
    def last_window(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self.recent[-1]) if self.recent else None

    def snapshot(self) -> Dict[str, Any]:
        """Cumulative closed-window account — the autopsy/bench view."""
        with self._lock:
            wall = self.wall_total
            secs = dict(self.totals)
            residual = wall - sum(secs.values())
            return {
                "windows": self.windows_closed,
                "steps": self.steps_total,
                "wall_s": round(wall, 4),
                "seconds": {c: round(v, 4) for c, v in secs.items()},
                "fractions": {c: round(v / wall, 4) if wall > 0 else 0.0
                              for c, v in secs.items()},
                "fraction": round(secs["compute"] / wall, 4)
                if wall > 0 else 0.0,
                "residual_s": round(residual, 6),
                "closed": self.max_residual_frac <= self.tolerance,
                "books_violations": self.books_violations,
                "tolerance": self.tolerance,
                "last_window": dict(self.recent[-1])
                if self.recent else None,
            }


# -- module seams (every caller goes through these; all no-op when the
#    plane is disabled or nothing has started) ---------------------------
def enabled() -> bool:
    return env_bool("GOODPUT", True)


def ledger(create: bool = True) -> Optional[GoodputLedger]:
    global _LEDGER
    if _LEDGER is None and create:
        with _LOCK:
            if _LEDGER is None:
                _LEDGER = GoodputLedger()
    return _LEDGER


def note_step_begin() -> None:
    if not enabled():
        return
    try:
        ledger().note_step_begin()
    except Exception:
        pass


def note_step_end(dt: Optional[float]) -> None:
    if not enabled() or dt is None:
        return
    try:
        ledger().note_step_end(dt)
    except Exception:
        pass


def note_checkpoint_stall(seconds: float) -> None:
    led = _LEDGER
    if led is None or not enabled():
        return
    try:
        led.note_checkpoint_stall(seconds)
    except Exception:
        pass


def note_remesh(seconds: float) -> None:
    led = _LEDGER
    if led is None or not enabled():
        return
    try:
        led.note_remesh(seconds)
    except Exception:
        pass


def flush() -> Optional[Dict[str, Any]]:
    led = _LEDGER
    if led is None:
        return None
    try:
        return led.flush()
    except Exception:
        return None


def snapshot(flush_open: bool = False) -> Optional[Dict[str, Any]]:
    """The cumulative ledger account, or None when the plane never ran.
    ``flush_open=True`` first folds the in-progress window in (autopsy,
    end-of-bench)."""
    led = _LEDGER
    if led is None:
        return None
    if flush_open:
        flush()
    try:
        return led.snapshot()
    except Exception:
        return None


def fleet_summary() -> Optional[Dict[str, Any]]:
    """Small per-rank doc for the fleet fan-in tree: the last closed
    window's productive fraction + dominating loss category."""
    led = _LEDGER
    if led is None:
        return None
    rec = led.last_window()
    if rec is None:
        return None
    return {"fraction": round(rec["fraction"], 4),
            "dominating": GoodputLedger.dominating(rec),
            "wall_s": round(rec["wall_s"], 4)}


def reset() -> None:
    """Tests: drop the singleton (a fresh ledger re-reads the knobs)."""
    global _LEDGER
    with _LOCK:
        _LEDGER = None
