"""Pure-JAX quantize/dequantize pairs for gradient transport.

EQuARX (arxiv 2506.17615) shows XLA-native block-wise quantized
collectives recover ~2x collective bandwidth on TPU with negligible
quality loss; the reference framework only ever shipped dtype casts
(``Compression.fp16``). Three codecs, each a pure function pair that
jits, vmaps and shards cleanly:

* :class:`BlockInt8Quantizer` — per-block ``absmax/127`` scale + int8
  payload (the EQuARX shape). ~3.94x smaller than fp32 at block 256.
  Max abs error per element is ``absmax_block / 254`` (half an int8
  step), i.e. relative error ≤ 1/254 against the block's largest
  magnitude.
* :class:`FP8Quantizer` — scaled cast to ``jnp.float8_e4m3fn`` /
  ``float8_e5m2`` (per-tensor ``absmax / dtype_max`` scale). 4x smaller
  than fp32 with a floating exponent per element; availability-gated on
  the installed jax.
* :class:`OneBitQuantizer` — sign bits packed 8-per-byte + the tensor's
  mean magnitude (1-bit SGD / signSGD style). ~32x smaller than fp32;
  only meaningful under error feedback
  (:mod:`horovod_tpu.compression.error_feedback`).

Shape/dtype contract: ``quantize(x) -> (Quantized(values, scales),
QuantSpec)`` where ``Quantized`` is a pytree of arrays (traceable,
gatherable) and ``QuantSpec`` is static python data (shape/dtype/pad)
that is identical on every shard of an SPMD program — so the pair can
live inside ``jit``/``shard_map`` with the spec closed over statically.
``dequantize(q, spec)`` restores the original shape/dtype.

Quantizer instances hash/compare by configuration so they can key
compile caches (``ops/mesh_collectives._cached_collective``).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.compression.base import Compressor


def _default_block_size() -> int:
    """Env-tunable default (docs/KNOBS.md): HVD_TPU_ name wins over the
    HOROVOD_ alias, 256 otherwise (scale overhead 4/256 = 1.6%)."""
    for key in ("HVD_TPU_COMPRESSION_BLOCK_SIZE",
                "HOROVOD_COMPRESSION_BLOCK_SIZE"):
        v = os.environ.get(key)
        if v:
            return int(v)
    return 256


class Quantized(NamedTuple):
    """Wire payload: the quantized values plus their scales. A pytree of
    arrays — safe to pass through jit boundaries and collectives."""

    values: jax.Array
    scales: jax.Array

    @property
    def wire_bytes(self) -> int:
        """Bytes this payload puts on the interconnect."""
        return (int(np.prod(self.values.shape)) * self.values.dtype.itemsize
                + int(np.prod(self.scales.shape)) * self.scales.dtype.itemsize)


class QuantSpec(NamedTuple):
    """Static reconstruction recipe: identical across SPMD shards."""

    shape: Tuple[int, ...]
    dtype: str
    pad: int


def _flatten(x) -> Tuple[jax.Array, QuantSpec]:
    x = jnp.asarray(x)
    spec = QuantSpec(shape=tuple(x.shape), dtype=jnp.dtype(x.dtype).name,
                     pad=0)
    return x.reshape(-1), spec


class Quantizer(Compressor):
    """Base for codecs whose payload is NOT sum-reducible on the wire.

    Transport layers must route these through quantized allgather paths
    (``collectives.quantized_allreduce``, ``device_allreduce`` with
    ``compression=``) — summing int8 payloads across different block
    scales is meaningless, unlike the fp16/bf16 casts.
    """

    name = "quantizer"

    def quantize(self, x) -> Tuple[Quantized, QuantSpec]:
        raise NotImplementedError

    def dequantize(self, q: Quantized, spec: QuantSpec):
        raise NotImplementedError

    def qdq(self, x):
        """quantize∘dequantize — the in-graph "simulated compression"
        used by error feedback and the traced (global-SPMD) regime."""
        q, spec = self.quantize(x)
        return self.dequantize(q, spec)

    # Compressor seam: payload is the Quantized pair, ctx the spec.
    def compress(self, tensor):
        return self.quantize(tensor)

    def decompress(self, tensor, ctx):
        return self.dequantize(tensor, ctx)

    def _config(self) -> tuple:
        return (type(self).__name__,)

    def __hash__(self):
        return hash(self._config())

    def __eq__(self, other):
        return isinstance(other, Quantizer) and \
            self._config() == other._config()

    def __repr__(self):
        return f"{type(self).__name__}{self._config()[1:]}"


class BlockInt8Quantizer(Quantizer):
    """Block-wise int8: flatten, pad to a block multiple, one fp32 scale
    per ``block_size`` elements (EQuARX-style). The codec itself runs as
    a fused Pallas kernel on TPU (:mod:`ops.pallas_quantize`;
    ``interpret=True`` exercises it on CPU), with a same-semantics XLA
    fallback elsewhere.

    Error bound: ``|x - qdq(x)| ≤ max|block| / 254`` elementwise.

    ``block_size=None`` (the ``Compression.int8`` default instance)
    resolves HVD_TPU_COMPRESSION_BLOCK_SIZE at USE time, matching every
    other knob's read-at-init semantics (docs/KNOBS.md) — an env change
    after import still takes effect, and config-keyed hashing (compile
    caches) tracks the resolved value.
    """

    name = "int8"

    def __init__(self, block_size: int = None, interpret: bool = False):
        if block_size is not None and int(block_size) <= 0:
            raise ValueError("block_size must be positive")
        self._block_size = int(block_size) if block_size is not None \
            else None
        self.interpret = interpret

    @property
    def block_size(self) -> int:
        return self._block_size if self._block_size is not None \
            else _default_block_size()

    def _config(self):
        return (type(self).__name__, self.block_size, self.interpret)

    def quantize(self, x):
        from horovod_tpu.ops.pallas_quantize import block_quantize
        flat, spec = _flatten(x)
        pad = (-flat.size) % self.block_size
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)])
        blocks = flat.reshape(-1, self.block_size)
        vals, scales = block_quantize(blocks, interpret=self.interpret)
        return Quantized(vals, scales), spec._replace(pad=pad)

    def dequantize(self, q, spec):
        from horovod_tpu.ops.pallas_quantize import block_dequantize
        flat = block_dequantize(q.values, q.scales,
                                interpret=self.interpret).reshape(-1)
        if spec.pad:
            flat = flat[:flat.size - spec.pad]
        return flat.reshape(spec.shape).astype(spec.dtype)


_FP8_MAX = {"e4m3": 448.0, "e5m2": 57344.0}


def fp8_supported() -> bool:
    return hasattr(jnp, "float8_e4m3fn") and hasattr(jnp, "float8_e5m2")


class FP8Quantizer(Quantizer):
    """Scaled cast to fp8: one per-tensor fp32 scale maps the absmax onto
    the format's max finite value, so the 4-5 exponent bits track each
    element's own magnitude (vs the int8 codec's shared block scale).
    ``e4m3`` (default) favors precision, ``e5m2`` dynamic range."""

    name = "fp8"

    def __init__(self, flavor: str = "e4m3"):
        if flavor not in _FP8_MAX:
            raise ValueError(f"fp8 flavor must be e4m3|e5m2, got {flavor!r}")
        if not fp8_supported():
            raise NotImplementedError(
                "this jax build has no jnp.float8_* dtypes; use "
                "Compression.int8 or Compression.bf16 instead")
        self.flavor = flavor
        self._dtype = jnp.float8_e4m3fn if flavor == "e4m3" \
            else jnp.float8_e5m2

    def _config(self):
        return (type(self).__name__, self.flavor)

    def quantize(self, x):
        flat, spec = _flatten(x)
        f = flat.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(f))
        scale = jnp.where(absmax > 0.0, absmax / _FP8_MAX[self.flavor], 1.0)
        vals = (f / scale).astype(self._dtype)
        return Quantized(vals, scale.reshape(1)), spec

    def dequantize(self, q, spec):
        flat = q.values.astype(jnp.float32) * q.scales[0]
        return flat.reshape(spec.shape).astype(spec.dtype)


class OneBitQuantizer(Quantizer):
    """sign(x) packed 8-per-byte + mean |x| (1-bit SGD): ~32x smaller
    than fp32. Biased on its own — compose with
    :class:`~horovod_tpu.compression.error_feedback.ErrorFeedback` so
    the residual carries what the sign bit drops."""

    name = "onebit"

    def quantize(self, x):
        flat, spec = _flatten(x)
        f = flat.astype(jnp.float32)
        mean = jnp.mean(jnp.abs(f)) if f.size else jnp.float32(0)
        pad = (-f.size) % 8
        bits = jnp.concatenate(
            [f >= 0, jnp.zeros((pad,), bool)]) if pad else (f >= 0)
        weights = (2 ** jnp.arange(8, dtype=jnp.uint32))[None, :]
        packed = jnp.sum(bits.reshape(-1, 8).astype(jnp.uint32) * weights,
                         axis=1).astype(jnp.uint8)
        return Quantized(packed, mean.reshape(1)), spec._replace(pad=pad)

    def dequantize(self, q, spec):
        bits = (q.values[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
        signs = bits.reshape(-1).astype(jnp.float32) * 2.0 - 1.0
        if spec.pad:
            signs = signs[:signs.size - spec.pad]
        return (signs * q.scales[0]).reshape(spec.shape).astype(spec.dtype)


def resolve_compressor(name: str):
    """Map a knob string (``--compression`` / HVD_BENCH_COMPRESSION) to a
    compressor: int8 | fp8 | fp8_e4m3 | fp8_e5m2 | onebit | fp16 | bf16 |
    none."""
    from horovod_tpu.compression.base import (BF16Compressor,
                                              FP16Compressor,
                                              NoneCompressor)
    key = (name or "none").lower()
    table = {
        "none": NoneCompressor,
        "fp16": FP16Compressor,
        "bf16": BF16Compressor,
        "int8": BlockInt8Quantizer(),
        "fp8": FP8Quantizer("e4m3") if fp8_supported() else None,
        "fp8_e4m3": FP8Quantizer("e4m3") if fp8_supported() else None,
        "fp8_e5m2": FP8Quantizer("e5m2") if fp8_supported() else None,
        "onebit": OneBitQuantizer(),
    }
    if key not in table:
        raise ValueError(
            f"unknown compression {name!r}; expected one of "
            f"{sorted(table)}")
    comp = table[key]
    if comp is None:
        raise NotImplementedError(
            f"compression {name!r} needs jnp.float8_* dtypes, absent "
            "from this jax build")
    return comp
