"""Compressor interface + dtype-cast compressors.

Reference: ``horovod/torch/compression.py:20-75`` — the ``Compressor``
base with ``Compression.none`` / ``.fp16`` compress/decompress pairs
around allreduce. These casts halve wire bytes at most; the real
bandwidth recovery lives in :mod:`horovod_tpu.compression.quantizers`
(block-wise int8 / fp8 / 1-bit, EQuARX-style).

The contract every compressor honors::

    payload, ctx = comp.compress(tensor)   # payload is what moves
    tensor ≈ comp.decompress(payload, ctx)

For the cast family the payload is a plain array the backend can
allreduce directly (sum in fp16/bf16 is well-defined). Quantizers
subclass :class:`Quantizer` instead — their payloads carry per-block
scales and sum on the wire is NOT meaningful, so the transport layers
route them through quantized allgather paths
(:func:`horovod_tpu.ops.collectives.quantized_allreduce`,
``device_allreduce(compression=)``).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def _astype(tensor, dtype):
    """Dtype cast for numpy and jax arrays alike (both honor .astype)."""
    return tensor.astype(dtype)


class Compressor:
    """Interface (reference: ``Compressor`` base, ``compression.py:20-33``)."""

    @staticmethod
    def compress(tensor) -> Tuple:
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Compress float32/float64 to float16 for transport
    (reference: ``compression.py:42-62``)."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.float16:
            return _astype(tensor, jnp.float16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else _astype(tensor, ctx)


class BF16Compressor(Compressor):
    """TPU-native 16-bit compression (no reference analog; bf16 keeps fp32's
    exponent range so gradient overflow handling is unnecessary)."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.bfloat16:
            return _astype(tensor, jnp.bfloat16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else _astype(tensor, ctx)
