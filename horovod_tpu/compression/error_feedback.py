"""Error feedback (EF) for lossy gradient compression.

Lossy codecs bias SGD: whatever the quantizer rounds away this step is
gone forever, and for aggressive codecs (1-bit) the bias kills
convergence outright. EF (1-bit SGD, Seide et al.; EF-SGD, Karimireddy
et al.) fixes this by carrying the compression error forward::

    acc      = grad + residual          # re-inject last step's error
    compressed = C(acc)                 # what the wire moves
    residual = acc - compressed         # carried to the next step

Every worker keeps its OWN residual (the error of compressing its own
contribution); the synchronized gradient is the reduction of the
compressed contributions.

Two ways to use it:

* ``DistributedGradTransform(compression=ErrorFeedback(Compression.int8))``
  — the :class:`ErrorFeedback` marker threads EF through the existing
  ``compression=`` seam: the transform's state grows a per-leaf residual
  pytree and the transport still moves quantized bytes where the regime
  allows (eager multi-process → quantized allgather wire; traced
  global-SPMD → in-graph quantize∘dequantize, since XLA already reduced
  the gradients from shardings).
* :func:`error_feedback_transform` — a standalone optax
  ``GradientTransformation`` composable anywhere in a chain.

Residuals live in fp32 regardless of the gradient dtype (the whole point
is keeping what the codec cannot represent), and non-floating leaves
pass through untouched.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.compression.base import Compressor
from horovod_tpu.compression.quantizers import Quantizer


class ErrorFeedback:
    """Marker wrapper for the ``compression=`` seam: ``inner`` is the
    actual codec; the consuming transform owns the residual state."""

    def __init__(self, inner: Compressor):
        if isinstance(inner, ErrorFeedback):
            raise ValueError("ErrorFeedback cannot wrap ErrorFeedback")
        self.inner = inner

    def __repr__(self):
        return f"ErrorFeedback({self.inner!r})"


class EFState(NamedTuple):
    residual: Any  # pytree matching params; None leaves = passthrough


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def init_residual(params):
    """fp32 zeros for every floating leaf, None for the rest."""
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32)
        if _is_float(p) else None, params)


def _qdq(comp: Compressor, x):
    """In-graph quantize∘dequantize through whichever codec interface
    ``comp`` exposes (Quantizer.qdq or cast compress/decompress)."""
    if isinstance(comp, Quantizer):
        return comp.qdq(x)
    payload, ctx = comp.compress(x)
    return comp.decompress(payload, ctx)


def ef_apply(comp: Compressor, updates, residual):
    """One EF round over a pytree: returns ``(compressed_updates,
    new_residual)``. Leaves with a None residual pass through."""

    def one(u, r):
        if r is None:
            return u, None
        acc = u.astype(jnp.float32) + r
        out = _qdq(comp, acc).astype(u.dtype)
        # residual measures the error of what the caller actually GETS —
        # including the cast back to the gradient dtype (for bf16 grads
        # that rounding is comparable to the int8 step itself)
        return out, acc - out.astype(jnp.float32)

    flat_u, treedef = jax.tree_util.tree_flatten(updates)
    flat_r = treedef.flatten_up_to(residual)
    pairs = [one(u, r) for u, r in zip(flat_u, flat_r)]
    new_u = jax.tree_util.tree_unflatten(treedef, [p[0] for p in pairs])
    new_r = jax.tree_util.tree_unflatten(treedef, [p[1] for p in pairs])
    return new_u, new_r


def error_feedback_transform(comp: Compressor
                             ) -> optax.GradientTransformation:
    """Standalone optax transform: compress updates with ``comp`` under
    error feedback. Chain it BEFORE the gradient sync so the residual is
    per-worker local (``optax.chain(error_feedback_transform(c), ...)``)."""

    def init_fn(params):
        return EFState(residual=init_residual(params))

    def update_fn(updates, state, params=None):
        del params
        new_updates, new_residual = ef_apply(comp, updates, state.residual)
        return new_updates, EFState(residual=new_residual)

    return optax.GradientTransformation(init_fn, update_fn)
