"""Gradient compression subsystem.

Replaces the cast-only module the reference shipped
(``horovod/torch/compression.py``) with a real codec layer:

* :mod:`~horovod_tpu.compression.base` — the ``Compressor`` contract +
  fp16/bf16 dtype casts (reference parity),
* :mod:`~horovod_tpu.compression.quantizers` — block-wise int8
  (EQuARX-style, Pallas-accelerated on TPU), fp8 (e4m3/e5m2) and 1-bit
  sign+mean codecs,
* :mod:`~horovod_tpu.compression.error_feedback` — residual-carrying EF
  so lossy codecs converge,
* :mod:`~horovod_tpu.compression.metrics` — pre/wire byte counters and
  the compression-ratio gauge on ``/metrics``.

Transport integration: ``DistributedGradTransform(compression=...)``
(and ``DistributedOptimizer``) accept any of these — including
``ErrorFeedback(...)``-wrapped codecs;
``ops.collectives.quantized_allreduce`` and
``ops.mesh_collectives.device_allreduce(compression=...)`` are the
quantized wire paths (see docs/PERF.md "Gradient compression").
"""

from horovod_tpu.compression.base import (  # noqa: F401
    BF16Compressor,
    Compressor,
    FP16Compressor,
    NoneCompressor,
)
from horovod_tpu.compression.quantizers import (  # noqa: F401
    BlockInt8Quantizer,
    FP8Quantizer,
    OneBitQuantizer,
    Quantized,
    QuantSpec,
    Quantizer,
    fp8_supported,
    resolve_compressor,
)
from horovod_tpu.compression.error_feedback import (  # noqa: F401
    EFState,
    ErrorFeedback,
    ef_apply,
    error_feedback_transform,
    init_residual,
)
from horovod_tpu.compression.metrics import (  # noqa: F401
    compression_ratio,
    record_compression,
)


class Compression:
    """Namespace matching the reference's public surface
    (``hvd.Compression.none`` / ``.fp16``; ``compression.py:65-75``),
    grown with the quantizing codecs. ``int8``/``onebit`` are default
    instances; construct :class:`BlockInt8Quantizer` /
    :class:`FP8Quantizer` directly for non-default block sizes or
    flavors."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = BlockInt8Quantizer()
    onebit = OneBitQuantizer()


if fp8_supported():
    Compression.fp8_e4m3 = FP8Quantizer("e4m3")
    Compression.fp8_e5m2 = FP8Quantizer("e5m2")
    Compression.fp8 = Compression.fp8_e4m3
