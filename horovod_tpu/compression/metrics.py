"""Compression observability: byte counters + ratio gauges.

Registered in the process-wide metrics registry
(:mod:`horovod_tpu.metrics.registry`), so the per-worker ``/metrics``
exporter and ``hvd.metrics_snapshot()`` pick them up with no extra
wiring:

* ``hvd_compression_pre_bytes_total{codec=...}`` — bytes the caller
  would have moved uncompressed,
* ``hvd_compression_wire_bytes_total{codec=...}`` — bytes actually
  put on the wire (values + scales),
* ``hvd_compression_ratio{codec=...}`` — cumulative pre/wire ratio
  (gauge, merged as ``mean`` across workers).

Byte accounting happens at the host boundary of each transport path
(eager enqueue, array-level mesh collective) from STATIC shapes —
nothing is recorded from inside traced code.
"""

from __future__ import annotations

from typing import Dict, Tuple

from horovod_tpu.metrics.registry import default_registry

_INSTRUMENTS: Dict[str, Tuple] = {}


def _codec_instruments(codec: str):
    inst = _INSTRUMENTS.get(codec)
    if inst is None:
        reg = default_registry()
        labels = {"codec": codec}
        inst = _INSTRUMENTS.setdefault(codec, (
            reg.counter("hvd_compression_pre_bytes_total",
                        help="bytes before gradient compression",
                        labels=labels),
            reg.counter("hvd_compression_wire_bytes_total",
                        help="bytes actually moved on the wire",
                        labels=labels),
            reg.gauge("hvd_compression_ratio",
                      help="cumulative pre/wire compression ratio",
                      labels=labels, agg="mean"),
        ))
    return inst


def record_compression(codec: str, pre_bytes: int, wire_bytes: int) -> None:
    """Account one compressed transfer; updates the cumulative ratio."""
    first = codec not in _INSTRUMENTS
    pre, wire, ratio = _codec_instruments(codec)
    pre.inc(pre_bytes)
    wire.inc(wire_bytes)
    if wire.value > 0:
        ratio.set(pre.value / wire.value)
    if first:
        # codec choice is a control-plane decision worth remembering in
        # a post-mortem; once per codec keeps the flight ring for the
        # per-collective evidence
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event("codec_choice", codec=codec, pre_bytes=pre_bytes,
                     wire_bytes=wire_bytes)


def compression_ratio(codec: str) -> float:
    """Cumulative ratio recorded so far for ``codec`` (0.0 if nothing
    was recorded yet)."""
    pre, wire, _ = _codec_instruments(codec)
    return (pre.value / wire.value) if wire.value > 0 else 0.0
