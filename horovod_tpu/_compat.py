"""Bridges over jax API drift, internal to horovod_tpu.

The codebase targets the newer-jax spellings — top-level ``jax.shard_map``
(with its ``check_vma`` kwarg) and ``jax.lax.axis_size`` — while older
environments ship ``jax.experimental.shard_map`` (kwarg ``check_rep``) and
no ``axis_size``. Every in-repo call site imports the two names from here
instead of reaching into ``jax`` directly, so the bridging never leaks into
the third-party module (other libraries in the process must see the stock
``jax`` surface, feature-detection and all).
"""

from __future__ import annotations

import functools

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    try:
        from jax.experimental.shard_map import shard_map as _legacy_shard_map

        @functools.wraps(_legacy_shard_map)
        def shard_map(*args, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _legacy_shard_map(*args, **kwargs)
    except ImportError:  # even older jax: informative error at call time
        def shard_map(*args, **kwargs):
            raise NotImplementedError(
                "this jax provides neither jax.shard_map nor "
                "jax.experimental.shard_map; horovod_tpu's manual-SPMD "
                "paths need one of the two")

if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        """Size of a named mesh axis from inside shard_map (newer jax
        reads it from static metadata; psum of ones is the classic
        equivalent and folds to a constant under jit)."""
        return jax.lax.psum(1, axis_name)
