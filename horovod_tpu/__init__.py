"""horovod_tpu — a TPU-native distributed training framework.

Horovod-class capabilities (reference: uber/horovod v0.22.1) re-designed for
TPU: the data plane is XLA collectives over ICI/DCN meshes instead of
NCCL/MPI rings; the host control plane is a C++ negotiation core over TCP;
parallelism (dp/tp/pp/sp/ep) is first-class via ``jax.sharding``.

Drop-in-familiar surface::

    import horovod_tpu as hvd
    hvd.init()
    ...
    grads = hvd.allreduce(grads, op=hvd.Average)

TPU-idiomatic surface::

    mesh = hvd.build_mesh(dp=-1, tp=4)
    tx = hvd.DistributedOptimizer(optax.adamw(1e-3))   # optax transform
"""

from horovod_tpu.version import __version__  # noqa: F401

# Lifecycle / identity (reference: horovod/common/basics.py)
from horovod_tpu.common.basics import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    cross_rank,
    cross_size,
    is_homogeneous,
    num_devices,
    global_device_count,
    start_timeline,
    stop_timeline,
    counters,
    engine_state,
    metrics_snapshot,
    stragglers,
    xla_built,
    tcp_core_built,
    gloo_built,
    mpi_built,
    nccl_built,
    ccl_built,
    cuda_built,
    rocm_built,
    ddl_built,
    sycl_built,
    mpi_enabled,
    gloo_enabled,
    mpi_threads_supported,
)

# Process sets (reference: horovod/common/process_sets.py)
from horovod_tpu.common.process_sets import (  # noqa: F401
    ProcessSet,
    add_process_set,
    remove_process_set,
    global_process_set,
    process_set_ids,
    get_process_set_by_id,
)

# Reduce ops (reference: horovod.torch.mpi_ops constants)
from horovod_tpu.ops.reduce_op import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
)

# Eager collectives (reference: horovod/torch/mpi_ops.py surface)
from horovod_tpu.ops.collectives import (  # noqa: F401
    allreduce,
    allreduce_async,
    grouped_allreduce,
    grouped_allreduce_async,
    allgather,
    allgather_async,
    broadcast,
    broadcast_async,
    alltoall,
    alltoall_async,
    reducescatter,
    reducescatter_async,
    poll,
    synchronize,
    join,
    barrier,
)

# Mesh / parallelism (TPU-native; no reference analog)
from horovod_tpu.parallel import (  # noqa: F401
    AXIS_ORDER,
    MeshSpec,
    build_mesh,
    dp_pp_mesh,
    single_axis_mesh,
    batch_sharding,
    logical_sharding,
)
# Unified parallelism plan (docs/PERF.md "Pipeline parallelism"): the
# frozen dp x pp / schedule / microbatch / comms decision object, the
# single compile seam behind the step factories, and the composed
# DP x PP pipelined train step.
from horovod_tpu.parallel.plan import (  # noqa: F401
    ParallelPlan,
    compile_step_with_plan,
)
from horovod_tpu.train.pipeline import (  # noqa: F401
    make_pipeline_train_step,
)
# Data-plane integrity (ISSUE 13; docs/TROUBLESHOOTING.md "My loss
# went NaN / my replicas disagree"): the numeric guardrail's spec and
# the cross-replica SDC canary
from horovod_tpu.train.guard import (  # noqa: F401
    GuardSpec,
    ReplicaCanary,
    param_digest,
)

# High-level training API (reference: horovod/torch/optimizer.py,
# horovod/tensorflow/__init__.py DistributedGradientTape)
from horovod_tpu.train.optimizer import (  # noqa: F401
    DistributedOptimizer,
    DistributedGradTransform,
    distributed_grad,
    broadcast_parameters,
    broadcast_optimizer_state,
    broadcast_object,
    allgather_object,
)
# Backprop/collective overlap engine (docs/PERF.md "Overlap &
# bucketing"): byte-budgeted gradient buckets, software-pipelined
# microbatch accumulation, fused dequantize+apply optimizers.
from horovod_tpu.train.buckets import (  # noqa: F401
    BucketPlan,
    plan_buckets,
)
from horovod_tpu.train.overlap import (  # noqa: F401
    bucketed_grad_sync,
    make_overlap_train_step,
    pipelined_accumulate,
)
# Mesh-path communication autotuner (docs/PERF.md "Autotuning"):
# topology-aware hierarchical collectives + online plan search with a
# persistent, fingerprint-keyed tuning cache.
from horovod_tpu.common.topology import (  # noqa: F401
    MeshTopology,
    detect_topology,
)
from horovod_tpu.train.autotune import (  # noqa: F401
    AutotuneOptions,
    Plan as AutotunePlan,
    make_parallel_train_step,
    parallel_candidate_plans,
)
from horovod_tpu.train.fused_apply import (  # noqa: F401
    fused_adam,
    fused_sgd,
)
# Gradient compression subsystem (quantizers + error feedback +
# quantized wire paths; reference analog: horovod/torch/compression.py,
# grown per EQuARX — see docs/PERF.md "Gradient compression")
from horovod_tpu.compression import (  # noqa: F401
    Compression,
    Compressor,
    ErrorFeedback,
)
from horovod_tpu.ops.collectives import (  # noqa: F401
    quantized_allreduce,
    quantized_allreduce_async,
    quantized_grouped_allreduce,
    quantized_grouped_allreduce_async,
)
from horovod_tpu.train.sync_batch_norm import SyncBatchNorm  # noqa: F401
# Durable sharded checkpointing (native subsystem; Checkpointer is the
# same class via the train.checkpoint back-compat shim, orbax optional)
from horovod_tpu.checkpoint import (  # noqa: F401
    CheckpointError,
    ShardedCheckpointer,
)
from horovod_tpu.train.checkpoint import Checkpointer  # noqa: F401
from horovod_tpu.train import callbacks  # noqa: F401

# Metrics & telemetry subsystem (docs/OBSERVABILITY.md; no reference
# analog — the reference's only runtime introspection is the timeline)
from horovod_tpu import metrics  # noqa: F401

# Flight recorder & hang autopsy (docs/OBSERVABILITY.md "Flight
# recorder & hang autopsy"): cross-rank trace merging, bounded event
# ring, hang watchdog with autopsy bundles
from horovod_tpu import diagnostics  # noqa: F401

# Elastic worker API (reference: horovod.elastic)
from horovod_tpu import elastic  # noqa: F401

# Zero-drop online serving (docs/SERVING.md): replica fleet, dynamic
# batcher, hedging router, hot weight swap (reference analog: the
# elastic driver's Spark/Ray serving integrations)
from horovod_tpu import serving  # noqa: F401
