"""Hang watchdog: a daemon thread that turns a silent stall into an
autopsy bundle.

The reference's stall inspector logs a warning on rank 0 and (optionally)
aborts; at pod scale the job usually just sits there, every rank waiting
on a different thing, until a human attaches a debugger to N hosts.  The
watchdog watches step progress (fed by the train-loop telemetry
callbacks and any explicit :func:`notify_progress` call); when no
progress lands for ``HVD_TPU_WATCHDOG_SECONDS`` (default 600; ``0``
disarms) it writes an autopsy bundle
(:func:`horovod_tpu.diagnostics.autopsy.write_autopsy`) — stacks for
every thread, flight-recorder ring, engine pending-tensor state,
metrics snapshot, merged timeline shards — and, on rank 0, every peer's
evidence over the exporter's ``/debug/*`` endpoints.

One bundle per stall: after triggering, the watchdog re-arms with the
trigger time as the new baseline, so a *persisting* hang produces one
bundle (plus one per subsequent watchdog period only if
``HVD_TPU_WATCHDOG_REPEAT=1``), not a bundle per check interval.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from horovod_tpu.common.logging import get_logger

DEFAULT_TIMEOUT_S = 600.0


def _env_timeout() -> float:
    from horovod_tpu.common.config import env_float
    return env_float("WATCHDOG_SECONDS", DEFAULT_TIMEOUT_S)


class Watchdog:
    """Progress watchdog with an autopsy trigger.

    Args:
      timeout_s: no-progress window before triggering; default from
        ``HVD_TPU_WATCHDOG_SECONDS``; <= 0 means the watchdog never
        starts (``start()`` is a no-op).
      autopsy_dir: bundle directory (default ``HVD_TPU_AUTOPSY_DIR``).
      on_trigger: replaces the default autopsy writer (tests).
      check_interval_s: poll period (default ``min(timeout/4, 10)``).
    """

    def __init__(self, timeout_s: Optional[float] = None,
                 autopsy_dir: Optional[str] = None,
                 on_trigger: Optional[Callable[[str], None]] = None,
                 check_interval_s: Optional[float] = None) -> None:
        self.timeout_s = _env_timeout() if timeout_s is None \
            else float(timeout_s)
        self.autopsy_dir = autopsy_dir
        self._on_trigger = on_trigger
        self._interval = check_interval_s or max(
            0.05, min(self.timeout_s / 4.0, 10.0))
        self._last_progress = time.monotonic()
        self._last_step: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.trigger_count = 0
        self.last_bundle: Optional[str] = None
        self._repeat = os.environ.get(
            "HVD_TPU_WATCHDOG_REPEAT", "") not in ("", "0")

    @property
    def armed(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Watchdog":
        if self.timeout_s <= 0 or self.armed:
            return self
        self._last_progress = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="hvd-tpu-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def notify_progress(self, step: Optional[int] = None) -> None:
        """Record a unit of forward progress (a completed train step, a
        committed checkpoint, ...). Cheap enough for hot loops."""
        self._last_progress = time.monotonic()
        if step is not None:
            self._last_step = step

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            idle = time.monotonic() - self._last_progress
            if idle <= self.timeout_s:
                continue
            self.trigger(f"no step progress for {idle:.0f}s "
                         f"(threshold {self.timeout_s:.0f}s, last step "
                         f"{self._last_step})")
            if not self._repeat:
                # a persisting hang: one bundle, then only log
                self._last_progress = time.monotonic() + self.timeout_s * 99
            else:
                self._last_progress = time.monotonic()

    def trigger(self, reason: str) -> Optional[str]:
        """Fire the autopsy now (also callable directly, e.g. from a
        signal handler). Returns the bundle path (None with a custom
        ``on_trigger``)."""
        self.trigger_count += 1
        get_logger().error("watchdog triggered: %s", reason)
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event("watchdog_trigger", reason=reason)
        if self._on_trigger is not None:
            try:
                self._on_trigger(reason)
            except Exception as e:
                get_logger().warning("watchdog on_trigger failed: %r", e)
            return None
        try:
            from horovod_tpu.diagnostics.autopsy import write_autopsy
            self.last_bundle = write_autopsy(self.autopsy_dir, reason)
        except Exception as e:
            get_logger().warning("watchdog autopsy failed: %r", e)
        return self.last_bundle


_WATCHDOG: Optional[Watchdog] = None
_SUSPENDED = False
_LOCK = threading.Lock()
# module-level progress stamp, kept even when no watchdog is armed —
# /healthz reports last-step age regardless of autopsy configuration
_LAST_PROGRESS_TS: Optional[float] = None
_LAST_STEP: Optional[int] = None


def ensure_watchdog() -> Optional[Watchdog]:
    """The process-wide watchdog, started on first call (armed by
    default from the train callbacks). Returns None when disarmed
    (``HVD_TPU_WATCHDOG_SECONDS=0``)."""
    global _WATCHDOG, _SUSPENDED
    with _LOCK:
        if _WATCHDOG is None:
            wd = Watchdog()
            if wd.timeout_s <= 0:
                return None
            _WATCHDOG = wd.start()
        _SUSPENDED = False
        return _WATCHDOG


def notify_progress(step: Optional[int] = None) -> None:
    """Feed the process-wide watchdog (no-op when none is armed) and
    stamp the module-level liveness clock either way."""
    global _LAST_PROGRESS_TS, _LAST_STEP
    _LAST_PROGRESS_TS = time.monotonic()
    if step is not None:
        _LAST_STEP = step
    wd = _WATCHDOG
    if wd is not None:
        wd.notify_progress(step)


def liveness() -> dict:
    """What ``/healthz`` reports beyond process-up: watchdog armed
    state + configured threshold, the last completed step and how long
    ago progress was last stamped (None before the first stamp — a
    process still compiling is not 'stalled')."""
    wd = _WATCHDOG
    age = None if _LAST_PROGRESS_TS is None \
        else time.monotonic() - _LAST_PROGRESS_TS
    return {"armed": bool(wd is not None and wd.armed),
            "timeout_s": _env_timeout(),
            "last_step": _LAST_STEP,
            "last_step_age_s": round(age, 3) if age is not None else None}


def suspend() -> None:
    """Stop the watchdog across a world teardown but REMEMBER it was
    armed (``hvd.shutdown``): an elastic shutdown→init cycle must not
    silently disarm hang detection for the recovered world."""
    global _SUSPENDED
    with _LOCK:
        if _WATCHDOG is not None:
            _WATCHDOG.stop()
            _SUSPENDED = True


def resume() -> None:
    """Re-arm a suspended watchdog with a fresh baseline
    (``hvd.init`` after an elastic re-mesh)."""
    with _LOCK:
        if _SUSPENDED and _WATCHDOG is not None:
            _WATCHDOG.notify_progress()
            _WATCHDOG.start()


def reset() -> None:
    """Stop and drop the process-wide watchdog (tests)."""
    global _WATCHDOG, _SUSPENDED, _LAST_PROGRESS_TS, _LAST_STEP
    with _LOCK:
        if _WATCHDOG is not None:
            _WATCHDOG.stop()
            _WATCHDOG = None
        _SUSPENDED = False
        _LAST_PROGRESS_TS = None
        _LAST_STEP = None
