"""Autopsy bundles: one directory that answers "which rank is stuck in
what".

Written by the hang watchdog (:mod:`horovod_tpu.diagnostics.watchdog`)
or on demand (:func:`write_autopsy`).  Every rank contributes its own
evidence with rank-suffixed filenames (so a shared filesystem
accumulates the whole picture even if cross-rank fetching fails):

* ``stacks_rank<r>.txt`` — ``faulthandler`` dump of every thread;
* ``flight_rank<r>.json`` — the flight-recorder ring;
* ``engine_rank<r>.json`` — engine counters + straggler report +
  pending-tensor state (``hvd_engine_state_json``: which tensors are
  waiting on which ranks — coordinator-only detail, like the reference's
  stall inspector);
* ``metrics_rank<r>.json`` — the full metrics snapshot;
* ``merged_trace.json`` — the per-rank timeline shards merged into one
  Perfetto trace (when shard tracing is on, docs/OBSERVABILITY.md).

Rank 0 additionally scrapes every peer's ``/debug/stacks``,
``/debug/flight`` and ``/debug/engine`` endpoints (served by the
metrics exporter, ``HVD_TPU_METRICS_PORT``) into ``peer_rank<r>_*``
files. Each rank writes ``summary_rank<r>.json``; rank 0's names the
suspect ranks/tensors (the coordinator sees every announcement).

All of it is best-effort: a hung process must never hang HARDER because
its autopsy failed.
"""

from __future__ import annotations

import faulthandler
import json
import os
import time
from typing import Any, Dict, List, Optional
from urllib.request import urlopen

from horovod_tpu.common.logging import get_logger
from horovod_tpu.diagnostics.flight_recorder import recorder

_FETCH_TIMEOUT_S = 5.0


def default_autopsy_dir() -> str:
    from horovod_tpu.common.config import env_str
    return env_str("AUTOPSY_DIR") or os.path.join(os.getcwd(),
                                                  "hvd_autopsy")


def _state():
    try:
        from horovod_tpu.common.basics import _state as st
        return st if st.initialized else None
    except Exception:
        return None


def _my_rank() -> int:
    st = _state()
    if st is not None:
        return st.rank
    from horovod_tpu.diagnostics.flight_recorder import _best_effort_rank
    return _best_effort_rank()


def _write(path: str, text: str) -> None:
    with open(path, "w") as f:
        f.write(text)


def _write_json(path: str, doc: Any) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, default=str)


def stacks_text() -> str:
    """All-thread stacks via faulthandler (works mid-hang: it walks
    frames without taking the GIL hostage beyond the dump)."""
    import tempfile
    # faulthandler needs a real fd, not a StringIO
    with tempfile.TemporaryFile(mode="w+") as f:
        faulthandler.dump_traceback(file=f, all_threads=True)
        f.seek(0)
        return f.read()


def engine_doc() -> Dict[str, Any]:
    """Counters + stragglers + pending-tensor state from the live
    backend (empty sections when not initialized / not the core)."""
    doc: Dict[str, Any] = {"rank": _my_rank(), "ts": time.time()}
    st = _state()
    be = st.backend if st is not None else None
    for key, attr in (("counters", "counters"),
                      ("stragglers", "stragglers"),
                      ("engine_state", "engine_state")):
        fn = getattr(be, attr, None)
        if fn is None:
            continue
        try:
            doc[key] = fn()
        except Exception as e:
            doc[key] = {"error": repr(e)}
    return doc


def metrics_doc() -> Dict[str, Any]:
    try:
        from horovod_tpu.common.basics import metrics_snapshot
        return metrics_snapshot()
    except Exception:
        from horovod_tpu.metrics.registry import default_registry
        return {"registry": default_registry().snapshot()}


def suspects_from_engine(engine: Dict[str, Any]) -> List[dict]:
    """Pending tensors → who is being waited on (the autopsy headline)."""
    out = []
    for dom in (engine.get("engine_state") or {}).get("domains", []):
        for p in dom.get("pending", []):
            out.append({"tensor": p.get("name"),
                        "waited_s": p.get("waited_s"),
                        "missing_ranks": p.get("missing_ranks", []),
                        "ready_ranks": p.get("ready_ranks", []),
                        "domain": dom.get("id")})
    out.sort(key=lambda p: -(p.get("waited_s") or 0.0))
    return out


def peer_debug_ports() -> Dict[int, tuple]:
    """rank → (host, port) for every OTHER rank's exporter.

    Port is ``HVD_TPU_METRICS_PORT + local_rank`` (exporter contract).
    Hosts: same-host ranks are ``127.0.0.1``; multi-host layouts need
    ``HVD_TPU_PEER_HOSTS`` (comma-separated host per rank) since worker
    processes don't learn peer hostnames from the launcher.
    """
    st = _state()
    if st is None or st.config is None:
        return {}
    base = getattr(st.config, "metrics_port", 0)
    if not base or base <= 0:
        return {}
    from horovod_tpu.metrics.exporter import peer_endpoint
    hosts_env = os.environ.get("HVD_TPU_PEER_HOSTS", "")
    hosts = [h.strip() for h in hosts_env.split(",")] if hosts_env else []
    out = {}
    for r in range(st.size):
        if r == st.rank:
            continue
        # single-host launches need no map (local_rank == global rank);
        # multi-host without PEER_HOSTS is skipped, not guessed
        if not hosts and st.cross_size > 1:
            continue
        out[r] = peer_endpoint(r, base, hosts)
    return out


def _fetch(url: str) -> Optional[bytes]:
    """Peer evidence fetch with backoff: a single transient connection
    reset must not silently lose a rank's stacks/flight dump from the
    bundle (the peer's exporter is a tiny threaded server that resets
    connections under accept bursts — exactly what a multi-rank autopsy
    causes)."""
    from urllib.error import HTTPError
    from urllib.request import Request

    from horovod_tpu import tracing
    from horovod_tpu.common.retry import retry_call
    headers = {}
    ctx = tracing.current()
    if ctx is not None:
        headers[tracing.TRACEPARENT] = ctx.traceparent
    try:
        return retry_call(
            lambda: urlopen(Request(url, headers=headers),
                            timeout=_FETCH_TIMEOUT_S).read(),
            site="autopsy.peer_fetch",
            retry_on=(OSError, TimeoutError),
            # an HTTP status (404/500: version skew, endpoint disabled)
            # will not heal with patience — and autopsy time is precious
            give_up_on=(HTTPError,),
            attempts=3, base_delay_s=0.2, max_delay_s=1.0,
            deadline_s=2.0 * _FETCH_TIMEOUT_S)
    except Exception as e:
        get_logger().warning("autopsy: fetch %s failed: %r", url, e)
        return None


def _collect_peers(bundle: str) -> tuple:
    """Returns ``(fetched, unreachable)`` rank lists; a peer is
    unreachable when none of its /debug endpoints answered even with
    retries — recorded in the summary so a bundle missing a rank's
    evidence says so explicitly instead of looking complete."""
    from horovod_tpu import tracing
    root = tracing.new_trace("autopsy")
    fetched, unreachable = [], []
    for r, (host, port) in sorted(peer_debug_ports().items()):
        base = f"http://{host}:{port}/debug"
        got_any = False
        # one child span per peer: which rank's evidence was slow (or
        # missing) is part of the autopsy's own story
        ctx = tracing.child(root, "autopsy")
        t0 = time.time()
        with tracing.activate(ctx):
            for kind, suffix in (("stacks", "txt"), ("flight", "json"),
                                 ("engine", "json")):
                body = _fetch(f"{base}/{kind}")
                if body is None:
                    continue
                got_any = True
                with open(os.path.join(
                        bundle,
                        f"peer_rank{r}_{kind}.{suffix}"), "wb") as f:
                    f.write(body)
        tracing.record_span("autopsy", "peer_fetch", ctx, start=t0,
                            dur_s=time.time() - t0, peer=r,
                            reached=got_any)
        (fetched if got_any else unreachable).append(r)
    return fetched, unreachable


def _merge_shards_into(bundle: str) -> Optional[str]:
    """Merge whatever timeline shards this host can see (shared-FS best
    case: all of them) into the bundle."""
    from horovod_tpu.common.config import get_config
    from horovod_tpu.common.timeline import shard_paths_for
    from horovod_tpu.diagnostics.merge import merge_shards
    cfg = get_config()
    if not cfg.timeline:
        return None
    st = _state()
    if st is not None and st.timeline is not None:
        st.timeline.flush()  # a live shard is mid-array on disk
    paths = [p for p in shard_paths_for(cfg.timeline)
             if os.path.exists(p)]
    # the core's rank-0 trace, if any (a FILE base only — a directory
    # base holds shards already picked up above)
    if os.path.isfile(cfg.timeline):
        paths.append(cfg.timeline)
    if not paths:
        return None
    out = os.path.join(bundle, "merged_trace.json")
    merge_shards(paths, out)
    return out


def write_autopsy(out_dir: Optional[str] = None, reason: str = "",
                  fetch_peers: Optional[bool] = None) -> str:
    """Write this rank's autopsy evidence into ``out_dir`` (default
    ``HVD_TPU_AUTOPSY_DIR`` / ``./hvd_autopsy``); returns the bundle
    directory.  Every step is individually best-effort."""
    rank = _my_rank()
    bundle = out_dir or default_autopsy_dir()
    os.makedirs(bundle, exist_ok=True)
    get_logger().error("writing autopsy bundle to %s (%s)", bundle,
                       reason or "on demand")

    def step(fn):
        try:
            return fn()
        except Exception as e:
            get_logger().warning("autopsy step failed: %r", e)
            return None

    step(lambda: _write(os.path.join(bundle, f"stacks_rank{rank}.txt"),
                        stacks_text()))
    step(lambda: recorder().dump_to(
        os.path.join(bundle, f"flight_rank{rank}.json")))
    engine = step(engine_doc) or {}
    step(lambda: _write_json(
        os.path.join(bundle, f"engine_rank{rank}.json"), engine))
    step(lambda: _write_json(
        os.path.join(bundle, f"metrics_rank{rank}.json"), metrics_doc()))
    step(lambda: _merge_shards_into(bundle))

    if fetch_peers is None:
        fetch_peers = rank == 0
    fetched: List[int] = []
    unreachable: List[int] = []
    if fetch_peers:
        fetched, unreachable = step(
            lambda: _collect_peers(bundle)) or ([], [])
        if unreachable:
            get_logger().warning(
                "autopsy: peers %s unreachable after retries; their "
                "evidence is missing from this bundle", unreachable)

    suspects = suspects_from_engine(engine)

    def _profiles():
        # a capture window that never completed (the job degraded,
        # started its trace, then hung/died) is closed NOW so the trace
        # bytes are on disk, then every capture record — path, trigger,
        # size — is embedded: the bundle ships its own "why" evidence
        # (docs/OBSERVABILITY.md "Deep profiling")
        from horovod_tpu import profiling
        profiling.finalize_open_capture(reason=f"autopsy: {reason}")
        return profiling.recent_captures()

    profiles = step(_profiles) or []

    def _anomalies():
        # "was it degrading before it died?" — the anomaly engine's
        # findings (step-time drift, throughput regression, persistent
        # straggler, exposed-comm growth; docs/OBSERVABILITY.md
        # "Anomaly engine") land in the summary so a hang autopsy also
        # reports the degradation history that preceded the stall
        from horovod_tpu.metrics.anomaly import recent_findings
        return recent_findings()

    anomalies = step(_anomalies) or []

    def _actions():
        # the autopilot decision trail (docs/OBSERVABILITY.md
        # "Autopilot"): a job that remediated itself — or decided not
        # to — and then died ships the evidence of what it tried,
        # gate inputs included
        from horovod_tpu.autopilot import recent_decisions
        return recent_decisions()

    actions = step(_actions) or []

    def _goodput():
        # the final ledger snapshot (docs/OBSERVABILITY.md "Goodput
        # ledger"): where the job's wall-clock went before it died,
        # with the open window flushed so the last partial window's
        # evidence is in the books too
        from horovod_tpu.metrics import goodput
        return goodput.snapshot(flush_open=True)

    goodput_snap = step(_goodput)

    def _driver_outage():
        # control-plane health at time of death (docs/ELASTIC.md
        # "Driver failover & takeover"): if the elastic driver has been
        # unreachable past the ride-through grace window, THAT is the
        # headline — the workers are orphaned, not stuck on each other
        from horovod_tpu.elastic import outage
        if not outage.enabled() or not outage.active():
            return None
        return {"age_s": round(outage.age_s(), 3),
                "grace_s": outage.grace_s(),
                "exceeded": outage.exceeded()}

    driver_outage = step(_driver_outage)

    def _exemplars():
        # the serving ledger's tail exemplars (docs/OBSERVABILITY.md
        # "Serving request ledger"): the worst requests per latency
        # window, each with its trace id and full stage breakdown — a
        # serving-plane death ships WHERE its slowest requests spent
        # their time
        from horovod_tpu.serving.ledger import exemplars
        return exemplars()

    exemplar_docs = step(_exemplars) or []
    if exemplar_docs:
        step(lambda: _write_json(
            os.path.join(bundle, f"exemplars_rank{rank}.json"),
            {"exemplars": exemplar_docs}))
    step(lambda: _write_json(
        os.path.join(bundle, f"summary_rank{rank}.json"), {
        "reason": reason,
        "rank": rank,
        "written_at": time.time(),
        "suspects": suspects,
        "anomalies": anomalies,
        "actions": actions,
        "profiles": profiles,
        "goodput": goodput_snap,
        "driver_outage": driver_outage,
        "exemplars": len(exemplar_docs),
        "peers_fetched": fetched,
        "peers_unreachable": unreachable,
    }))
    if profiles:
        get_logger().error(
            "autopsy: %d device-trace capture(s) available; last: %s",
            len(profiles), profiles[-1].get("path"))
    if anomalies:
        last = anomalies[-1]
        get_logger().error(
            "autopsy: %d anomaly finding(s) preceded this bundle; last: "
            "%s at step %s", len(anomalies), last.get("kind"),
            last.get("step"))
    if actions:
        last = actions[-1]
        get_logger().error(
            "autopsy: %d autopilot decision(s) preceded this bundle; "
            "last: %s %s (%s)", len(actions), last.get("policy"),
            last.get("outcome"), last.get("action"))
    if driver_outage and driver_outage.get("exceeded"):
        get_logger().error(
            "autopsy: driver dead > grace (unreachable %.1fs, grace "
            "%.0fs) — the supervisor is not coming back; see "
            "docs/TROUBLESHOOTING.md \"My driver died\"",
            driver_outage["age_s"], driver_outage["grace_s"])
    if suspects:
        top = suspects[0]
        get_logger().error(
            "autopsy: tensor %r has waited %.1fs on ranks %s",
            top["tensor"], top.get("waited_s") or 0.0,
            top.get("missing_ranks"))
    return bundle
