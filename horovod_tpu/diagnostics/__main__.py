"""CLI for the diagnostics subsystem.

``python -m horovod_tpu.diagnostics merge [-o OUT] SHARD... | --dir DIR``
    Fold per-rank timeline shards into one Perfetto/chrome trace.

``python -m horovod_tpu.diagnostics flight DUMP.json``
    Summarize a flight-recorder dump (event counts per kind, tail).

``python -m horovod_tpu.diagnostics timeline --dir DIR [--obs-dir D]
[--reqlog PATH]... [-o OUT]``
    The merged black-box timeline (docs/OBSERVABILITY.md "Causal
    tracing"): flight dumps + timeline shards found under ``--dir``,
    plus the serving request log(s), the autopilot actions JSONL and
    the re-mesh history from ``--obs-dir``, folded into ONE
    skew-corrected Perfetto trace.

``python -m horovod_tpu.diagnostics trace ID --dir DIR [--obs-dir D]
[--reqlog PATH]...``
    Print one trace id's causal tree with per-hop latency attribution
    (a trace id prefix is accepted when unambiguous).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _cmd_merge(args: argparse.Namespace) -> int:
    from horovod_tpu.diagnostics.merge import (find_shards, merge_shards)
    paths = list(args.shards)
    if args.dir:
        paths.extend(find_shards(args.dir))
    if not paths:
        print("no shards given (pass shard files or --dir)",
              file=sys.stderr)
        return 2
    out = args.output
    if not out:
        import os
        base = args.dir or os.path.dirname(paths[0]) or "."
        out = os.path.join(base, "merged_trace.json")
    doc = merge_shards(paths, out)
    pids = {ev.get("pid") for ev in doc["traceEvents"]
            if ev.get("ph") != "M"}
    print(f"merged {len(paths)} shard(s), "
          f"{len(doc['traceEvents'])} events, {len(pids)} track(s) "
          f"-> {out}")
    return 0


def _cmd_flight(args: argparse.Namespace) -> int:
    with open(args.dump) as f:
        doc = json.load(f)
    events = doc.get("events", [])
    kinds: dict = {}
    for ev in events:
        kinds[ev.get("kind", "?")] = kinds.get(ev.get("kind", "?"), 0) + 1
    print(f"rank {doc.get('rank')}: {len(events)} events "
          f"({doc.get('dropped', 0)} dropped, capacity "
          f"{doc.get('capacity')})")
    for kind, n in sorted(kinds.items(), key=lambda kv: -kv[1]):
        print(f"  {kind}: {n}")
    for ev in events[-args.tail:]:
        print(" ", json.dumps(ev, default=str))
    return 0


def _plane_paths(args):
    """(flight dumps, shards) under ``--dir``: flight dumps by their
    ``*flight*rank*.json`` naming, everything else rank-named is a
    timeline shard."""
    from horovod_tpu.diagnostics.merge import find_shards
    from horovod_tpu.tracing.reader import find_flight_dumps
    flights, shards = [], []
    for d in args.dir or []:
        flights.extend(find_flight_dumps(d))
        shards.extend(p for p in find_shards(d)
                      if "flight" not in os.path.basename(p).lower())
    flights.extend(args.flight or [])
    return flights, shards


def _cmd_timeline(args: argparse.Namespace) -> int:
    from horovod_tpu.tracing.reader import build_timeline
    flights, shards = _plane_paths(args)
    if not (flights or shards or args.reqlog or args.obs_dir):
        print("no planes given (pass --dir/--flight/--reqlog/--obs-dir)",
              file=sys.stderr)
        return 2
    out = args.output
    if not out:
        base = (args.dir[0] if args.dir else
                (args.obs_dir or "."))
        out = os.path.join(base, "merged_timeline.json")
    doc = build_timeline(flight_paths=flights, shard_paths=shards,
                         reqlog_paths=args.reqlog or [],
                         obs_dir=args.obs_dir, out_path=out)
    tracks = {ev.get("pid") for ev in doc["traceEvents"]
              if ev.get("ph") != "M"}
    print(f"merged timeline: {len(flights)} flight dump(s), "
          f"{len(shards)} shard(s), {len(args.reqlog or [])} request "
          f"log(s), obs={'yes' if args.obs_dir else 'no'} -> "
          f"{len(doc['traceEvents'])} events on {len(tracks)} track(s) "
          f"-> {out}")
    return 0


def _cmd_trace(args: argparse.Namespace) -> int:
    from horovod_tpu.tracing.reader import collect, render_trace
    flights, _shards = _plane_paths(args)
    data = collect(flight_paths=flights, obs_dir=args.obs_dir,
                   reqlog_paths=args.reqlog or [])
    ids = sorted({r["trace"] for r in data["spans"] + data["points"]})
    matches = [t for t in ids if t.startswith(args.trace_id)]
    if not matches:
        print(f"trace {args.trace_id!r} not found "
              f"({len(ids)} trace id(s) in the given planes)",
              file=sys.stderr)
        return 1
    if len(matches) > 1:
        print(f"trace prefix {args.trace_id!r} is ambiguous: "
              f"{', '.join(m[:12] for m in matches)}", file=sys.stderr)
        return 2
    trace_id = matches[0]
    filtered = {
        "spans": [s for s in data["spans"] if s["trace"] == trace_id],
        "points": [p for p in data["points"] if p["trace"] == trace_id],
    }
    print(render_trace(trace_id, filtered))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m horovod_tpu.diagnostics")
    sub = ap.add_subparsers(dest="cmd", required=True)

    mp = sub.add_parser("merge", help="merge per-rank timeline shards")
    mp.add_argument("shards", nargs="*", help="shard files")
    mp.add_argument("--dir", help="directory to glob shards from")
    mp.add_argument("-o", "--output", help="merged trace path")
    mp.set_defaults(fn=_cmd_merge)

    fp = sub.add_parser("flight", help="summarize a flight dump")
    fp.add_argument("dump")
    fp.add_argument("--tail", type=int, default=10,
                    help="print the last N events")
    fp.set_defaults(fn=_cmd_flight)

    def plane_args(p):
        p.add_argument("--dir", action="append",
                       help="directory holding flight dumps and/or "
                            "timeline shards (repeatable)")
        p.add_argument("--flight", action="append",
                       help="explicit flight dump path (repeatable)")
        p.add_argument("--reqlog", action="append",
                       help="serving request log JSONL (repeatable; "
                            "the rotated .1 generation is read too)")
        p.add_argument("--obs-dir",
                       help="HVD_TPU_OBS_DIR (actions JSONL + re-mesh "
                            "history)")

    tp = sub.add_parser("timeline",
                        help="merge every evidence plane into one "
                             "skew-corrected Perfetto trace")
    plane_args(tp)
    tp.add_argument("-o", "--output", help="merged trace path")
    tp.set_defaults(fn=_cmd_timeline)

    cp = sub.add_parser("trace",
                        help="print one trace id's causal tree with "
                             "per-hop latency attribution")
    cp.add_argument("trace_id", help="trace id (prefix ok)")
    plane_args(cp)
    cp.set_defaults(fn=_cmd_trace)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
