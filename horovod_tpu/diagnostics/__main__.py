"""CLI for the diagnostics subsystem.

``python -m horovod_tpu.diagnostics merge [-o OUT] SHARD... | --dir DIR``
    Fold per-rank timeline shards into one Perfetto/chrome trace.

``python -m horovod_tpu.diagnostics flight DUMP.json``
    Summarize a flight-recorder dump (event counts per kind, tail).
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_merge(args: argparse.Namespace) -> int:
    from horovod_tpu.diagnostics.merge import (find_shards, merge_shards)
    paths = list(args.shards)
    if args.dir:
        paths.extend(find_shards(args.dir))
    if not paths:
        print("no shards given (pass shard files or --dir)",
              file=sys.stderr)
        return 2
    out = args.output
    if not out:
        import os
        base = args.dir or os.path.dirname(paths[0]) or "."
        out = os.path.join(base, "merged_trace.json")
    doc = merge_shards(paths, out)
    pids = {ev.get("pid") for ev in doc["traceEvents"]
            if ev.get("ph") != "M"}
    print(f"merged {len(paths)} shard(s), "
          f"{len(doc['traceEvents'])} events, {len(pids)} track(s) "
          f"-> {out}")
    return 0


def _cmd_flight(args: argparse.Namespace) -> int:
    with open(args.dump) as f:
        doc = json.load(f)
    events = doc.get("events", [])
    kinds: dict = {}
    for ev in events:
        kinds[ev.get("kind", "?")] = kinds.get(ev.get("kind", "?"), 0) + 1
    print(f"rank {doc.get('rank')}: {len(events)} events "
          f"({doc.get('dropped', 0)} dropped, capacity "
          f"{doc.get('capacity')})")
    for kind, n in sorted(kinds.items(), key=lambda kv: -kv[1]):
        print(f"  {kind}: {n}")
    for ev in events[-args.tail:]:
        print(" ", json.dumps(ev, default=str))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m horovod_tpu.diagnostics")
    sub = ap.add_subparsers(dest="cmd", required=True)

    mp = sub.add_parser("merge", help="merge per-rank timeline shards")
    mp.add_argument("shards", nargs="*", help="shard files")
    mp.add_argument("--dir", help="directory to glob shards from")
    mp.add_argument("-o", "--output", help="merged trace path")
    mp.set_defaults(fn=_cmd_merge)

    fp = sub.add_parser("flight", help="summarize a flight dump")
    fp.add_argument("dump")
    fp.add_argument("--tail", type=int, default=10,
                    help="print the last N events")
    fp.set_defaults(fn=_cmd_flight)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
