"""Flight recorder & hang autopsy: the post-mortem observability layer.

Three cooperating parts (docs/OBSERVABILITY.md "Flight recorder & hang
autopsy"):

* **Cross-rank trace** — every rank can write a timeline shard
  (``HVD_TPU_TIMELINE_ALL_RANKS``) with per-collective span ids
  (:mod:`.spans`) and wall-clock anchors (:mod:`.clock`);
  :mod:`.merge` folds N shards into one Perfetto trace, one track per
  rank, the same collective correlated across tracks.
* **Flight recorder** (:mod:`.flight_recorder`) — a bounded in-memory
  ring of recent control-plane events (collective enqueue/complete,
  step begin/end, checkpoint save/commit, elastic re-mesh, codec
  choice), dumpable on demand and automatically on crash.
* **Hang watchdog** (:mod:`.watchdog`) + **autopsy** (:mod:`.autopsy`)
  — no step progress for ``HVD_TPU_WATCHDOG_SECONDS`` writes a bundle
  with per-rank stacks, engine pending-tensor state, the flight dump, a
  metrics snapshot and the merged trace; rank 0 also fetches every
  peer's evidence over the exporter's ``/debug/*`` endpoints.

CLI: ``python -m horovod_tpu.diagnostics merge ...``.
"""

from horovod_tpu.diagnostics.flight_recorder import (  # noqa: F401
    FlightRecorder,
    install_crash_hooks,
    record_event,
    recorder,
)
from horovod_tpu.diagnostics.spans import (  # noqa: F401
    active_span,
    current_span,
    next_span,
)
from horovod_tpu.diagnostics.clock import estimate_wall_offset  # noqa: F401
from horovod_tpu.diagnostics.merge import (  # noqa: F401
    merge_directory,
    merge_shards,
)
from horovod_tpu.diagnostics.watchdog import (  # noqa: F401
    Watchdog,
    ensure_watchdog,
    notify_progress,
)
from horovod_tpu.diagnostics.autopsy import write_autopsy  # noqa: F401
