"""Always-on flight recorder: a bounded ring of recent control-plane events.

The post-mortem complement of the live metrics layer
(``docs/OBSERVABILITY.md``): when a job crashes or hangs, the last few
thousand control-plane events — collective enqueue/complete with span
ids, step begin/end, checkpoint save/commit, elastic re-mesh,
compression codec choices — are what turn "it stopped" into "rank 3
enqueued ``grads.7`` and never saw it complete".  The reference has no
analog; its closest artifact is the rank-0 timeline, which must be
enabled ahead of time and dies with the process.

Design constraints:

* **bounded** — ``HVD_TPU_FLIGHT_RECORDER_SIZE`` events (default 4096),
  drop-oldest; memory use is O(capacity), independent of run length;
* **lock-cheap** — one short critical section per event (a deque append
  + a counter); no allocation beyond the event dict itself, no I/O;
* **always dumpable** — :func:`dump` from any thread at any time (the
  watchdog calls it mid-hang), :func:`install_crash_hooks` wires an
  excepthook so an uncaught exception leaves a dump on disk.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

DEFAULT_CAPACITY = 4096


def _env_capacity() -> int:
    from horovod_tpu.common.config import env_int
    cap = env_int("FLIGHT_RECORDER_SIZE", DEFAULT_CAPACITY)
    return max(cap, 1)


class FlightRecorder:
    """Thread-safe bounded event ring (drop-oldest)."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = int(capacity) if capacity else _env_capacity()
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=self.capacity)
        self._lock = threading.Lock()
        self._dropped = 0
        self._seq = 0

    def record(self, kind: str, **fields: Any) -> None:
        """Append one event; never raises, never blocks on I/O.  The
        thread's ACTIVE trace context (docs/OBSERVABILITY.md "Causal
        tracing") is stamped in as ``trace``/``span`` unless the caller
        already carries explicit trace fields."""
        ev = {"ts": time.time(), "kind": kind}
        if fields:
            ev.update(fields)
        if "trace" not in ev:
            try:
                from horovod_tpu import tracing
                ctx = tracing.current()
                if ctx is not None:
                    ev.update(ctx.fields())
            except Exception:
                pass
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(ev)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def events(self) -> List[dict]:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def dump(self) -> Dict[str, Any]:
        """Self-describing dump document (what lands in the autopsy
        bundle and on ``/debug/flight``)."""
        with self._lock:
            events = list(self._ring)
            dropped = self._dropped
        return {
            "rank": _best_effort_rank(),
            "capacity": self.capacity,
            "dropped": dropped,
            "recorded": len(events),
            "dumped_at": time.time(),
            # the same per-rank wall offset the timeline shards carry
            # (diagnostics/clock.py): the merged timeline maps flight
            # evidence onto the coordinator's clock with it, so
            # cross-rank flight events align with shard spans instead
            # of drifting by host clock skew
            "wall_offset_s": wall_offset(),
            "events": events,
        }

    def dump_to(self, path: str) -> str:
        doc = self.dump()
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        return path

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0


def _best_effort_rank() -> int:
    try:
        from horovod_tpu.common.basics import _state
        if _state.initialized:
            return _state.rank
    except Exception:
        pass
    v = os.environ.get("HVD_TPU_RANK", os.environ.get("HOROVOD_RANK", "0"))
    try:
        return int(v)
    except ValueError:
        return 0


_WALL_OFFSET = 0.0


def set_wall_offset(seconds: float) -> None:
    """Record this rank's estimated ``my_wall - coordinator_wall``
    (measured once at init by :mod:`horovod_tpu.diagnostics.clock` and
    shared with the timeline shards) so flight dumps are mergeable onto
    the coordinator's clock."""
    global _WALL_OFFSET
    _WALL_OFFSET = float(seconds)


def wall_offset() -> float:
    """The recorded offset, with ``HVD_TPU_CLOCK_OFFSET_S`` overriding
    live (same contract as the shard anchor: tests inject known skew,
    operators pin NTP-disciplined fleets to 0)."""
    forced = os.environ.get("HVD_TPU_CLOCK_OFFSET_S")
    if forced not in (None, ""):
        try:
            return float(forced)
        except ValueError:
            pass
    return _WALL_OFFSET


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def recorder() -> FlightRecorder:
    """The process-wide flight recorder (created on first use)."""
    global _RECORDER
    if _RECORDER is None:
        with _RECORDER_LOCK:
            if _RECORDER is None:
                _RECORDER = FlightRecorder()
    return _RECORDER


def record_event(kind: str, **fields: Any) -> None:
    """Module-level convenience used by the instrumented call sites
    (collectives, callbacks, checkpoint store, elastic)."""
    try:
        recorder().record(kind, **fields)
    except Exception:
        pass  # the recorder must never take down the caller


def crash_dump_path() -> str:
    """Where crash hooks drop the flight dump: the autopsy directory
    (``HVD_TPU_AUTOPSY_DIR``, default ``./hvd_autopsy`` — one contained
    place, not loose files in the CWD), created on demand."""
    from horovod_tpu.common.config import env_str
    base = env_str("AUTOPSY_DIR") or os.path.join(os.getcwd(),
                                                  "hvd_autopsy")
    try:
        os.makedirs(base, exist_ok=True)
    except OSError:
        base = "."
    return os.path.join(base, f"hvd_flight_rank{_best_effort_rank()}.json")


_hooks_installed = False


def install_crash_hooks() -> None:
    """Chain excepthooks (main thread + threading) so an uncaught
    exception dumps the flight ring to disk before the process dies;
    idempotent.  ``HVD_TPU_FLIGHT_DUMP_ON_EXIT=1`` additionally dumps at
    every interpreter exit (atexit), crash or not."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    prev_hook = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            record_event("crash", error=repr(exc))
            recorder().dump_to(crash_dump_path())
        except Exception:
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _hook

    prev_thook = threading.excepthook

    def _thook(args):
        try:
            record_event("thread_crash", error=repr(args.exc_value),
                         thread=getattr(args.thread, "name", "?"))
            recorder().dump_to(crash_dump_path())
        except Exception:
            pass
        prev_thook(args)

    threading.excepthook = _thook

    if os.environ.get("HVD_TPU_FLIGHT_DUMP_ON_EXIT", "") not in ("", "0"):
        import atexit

        def _atexit_dump():
            try:
                recorder().dump_to(crash_dump_path())
            except Exception:
                pass

        atexit.register(_atexit_dump)
