"""Cross-rank timeline merger: N per-rank shards → one Perfetto trace.

Each shard is a chrome-tracing JSON array written by
:class:`horovod_tpu.common.timeline.Timeline` (host shards, any rank) or
by the C++ engine's timeline (rank 0, negotiation phases).  A shard's
first event is a ``SHARD_META`` instant carrying the rank, the source
(``host``/``core``), a wall-clock anchor (``epoch_us`` = wall time at
the meta event, whose own ``ts`` is the matching shard-relative
timestamp) and the estimated wall offset to the coordinator
(:mod:`horovod_tpu.diagnostics.clock`).

The merger maps every event onto the coordinator's wall clock::

    wall_us(ev) = (epoch_us - wall_offset_us) + (ev.ts - meta.ts)

then rebases to the earliest event and assigns one process track per
shard (``pid`` = rank where known), named ``rank N`` / ``rank N (core)``
via ``process_name`` metadata so Perfetto shows one track per rank with
the same collective's spans (matched by ``args.span``) correlated
across tracks.

Shards from crashed ranks are commonly truncated mid-array; the loader
repairs unterminated JSON instead of dropping the evidence.

CLI: ``python -m horovod_tpu.diagnostics merge -o merged.json SHARD...``
(or ``--dir DIR`` to glob ``*timeline*rank*.json`` shards).
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional, Sequence

SHARD_META = "SHARD_META"


def load_shard(path: str) -> List[dict]:
    """Parse one shard, repairing a truncated (crash-cut) JSON array."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        repaired = text.rstrip().rstrip(",")
        if not repaired.startswith("["):
            raise
        try:
            doc = json.loads(repaired + "]")
        except ValueError:
            # cut mid-object: drop the partial trailing line
            lines = repaired.splitlines()
            doc = json.loads("\n".join(lines[:-1]).rstrip().rstrip(",")
                             + "]")
    if isinstance(doc, dict):  # tolerate {"traceEvents": [...]}
        doc = doc.get("traceEvents", [])
    # writers close the array with a bare {} sentinel — drop fillers
    return [ev for ev in doc if isinstance(ev, dict) and ev.get("ph")]


def _shard_meta(events: List[dict], path: str) -> Dict[str, Any]:
    for ev in events:
        if ev.get("name") == SHARD_META:
            args = ev.get("args", {}) or {}
            return {
                "rank": args.get("rank"),
                "source": args.get("source", "host"),
                "epoch_us": args.get("epoch_us"),
                "wall_offset_us": args.get("wall_offset_us", 0.0),
                "anchor_ts": ev.get("ts", 0.0),
            }
    m = re.search(r"rank[._-]?(\d+)", os.path.basename(path))
    return {"rank": int(m.group(1)) if m else None, "source": "host",
            "epoch_us": None, "wall_offset_us": 0.0, "anchor_ts": 0.0}


def merge_shards(paths: Sequence[str],
                 out_path: Optional[str] = None,
                 extra_tracks: Optional[Sequence[tuple]] = None
                 ) -> Dict[str, Any]:
    """Fold shards into one chrome trace document (also written to
    ``out_path`` when given).  Returns the document.

    ``extra_tracks`` adds non-shard planes (the unified timeline's
    flight dumps, request logs, action/remesh history — see
    :mod:`horovod_tpu.tracing.reader`): a sequence of ``(label,
    sort_index, events)`` where each event already carries an ABSOLUTE
    wall-clock ``ts`` in µs on the coordinator's clock (the caller
    applied its plane's offset); they are rebased together with the
    shard events so every plane shares one t=0."""
    shards = []
    for i, path in enumerate(sorted(paths)):
        try:
            events = load_shard(path)
        except (OSError, ValueError) as e:
            # one unreadable shard (a rank that died with an empty or
            # garbled file) must not cost the other N-1 ranks' evidence
            from horovod_tpu.common.logging import get_logger
            get_logger().warning("merge: skipping unreadable shard %s "
                                 "(%r)", path, e)
            continue
        meta = _shard_meta(events, path)
        if extra_tracks and meta["epoch_us"] is None:
            # the extras carry absolute wall-clock µs; an anchor-less
            # shard only has shard-relative time, and mixing the two
            # scales would rebase the whole timeline ~epoch apart —
            # drop it loudly rather than render an unusable trace
            # (plain shard-only merges keep the old relative behavior)
            from horovod_tpu.common.logging import get_logger
            get_logger().warning(
                "merge: shard %s has no SHARD_META wall anchor; "
                "skipping it in the multi-plane timeline", path)
            continue
        rank = meta["rank"] if meta["rank"] is not None else i
        shards.append((path, events, meta, rank))

    # one pid per shard; collisions (rank 0 host shard + rank 0 core
    # trace) get distinct pids so their tracks never interleave B/E
    # stacks, but stay adjacent via process_sort_index
    used_pids = set()
    merged: List[dict] = []
    placed = []  # (events_with_pid, meta)
    for path, events, meta, rank in shards:
        pid = rank
        while pid in used_pids:
            pid += 1000
        used_pids.add(pid)
        label = f"rank {rank}" + (
            " (core)" if meta["source"] == "core" else "")
        merged.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": "meta", "args": {"name": label}})
        merged.append({"ph": "M", "name": "process_sort_index",
                       "pid": pid, "tid": "meta",
                       "args": {"sort_index": rank}})
        placed.append((pid, events, meta))

    # the extra planes get their own tracks past the shard pid space
    next_pid = 10_000
    for label, sort_index, events in (extra_tracks or ()):
        pid = next_pid
        next_pid += 1
        merged.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": "meta", "args": {"name": label}})
        merged.append({"ph": "M", "name": "process_sort_index",
                       "pid": pid, "tid": "meta",
                       "args": {"sort_index": sort_index}})
        placed.append((pid, list(events),
                       {"epoch_us": None, "wall_offset_us": 0.0,
                        "anchor_ts": 0.0}))

    # map onto the coordinator's wall clock where anchors exist
    timed = []
    for pid, events, meta in placed:
        for ev in events:
            if ev.get("name") == SHARD_META or ev.get("ph") == "M":
                continue
            ts = float(ev.get("ts", 0.0))
            if meta["epoch_us"] is not None:
                ts = (float(meta["epoch_us"])
                      - float(meta["wall_offset_us"] or 0.0)
                      + (ts - float(meta["anchor_ts"] or 0.0)))
            out = dict(ev)
            out["pid"] = pid
            out["ts"] = ts
            timed.append(out)

    if timed:  # rebase so the trace starts at t=0 (viewers like it)
        t0 = min(ev["ts"] for ev in timed)
        for ev in timed:
            ev["ts"] = ev["ts"] - t0
    timed.sort(key=lambda ev: ev["ts"])
    merged.extend(timed)

    doc = {"traceEvents": merged, "displayTimeUnit": "ms"}
    if out_path:
        # pid-unique tmp: two ranks' watchdogs may merge into the same
        # shared-FS target concurrently; each rename stays atomic
        tmp = f"{out_path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        try:
            os.replace(tmp, out_path)
        except OSError:
            pass
    return doc


def find_shards(directory: str) -> List[str]:
    """Shard files under ``directory`` (the per-rank naming both the
    host timeline and bench use: ``*rank<r>*.json``), excluding
    previously merged outputs."""
    out = []
    for path in glob.glob(os.path.join(directory, "*.json")):
        base = os.path.basename(path)
        if "merged" in base:
            continue
        if re.search(r"rank[._-]?\d+", base):
            out.append(path)
    return sorted(out)


def merge_directory(directory: str,
                    out_path: Optional[str] = None) -> Optional[str]:
    """Merge every shard found in ``directory`` into
    ``out_path`` (default ``<directory>/merged_trace.json``).  Returns
    the output path, or None when no shards exist."""
    paths = find_shards(directory)
    if not paths:
        return None
    out_path = out_path or os.path.join(directory, "merged_trace.json")
    merge_shards(paths, out_path)
    return out_path
