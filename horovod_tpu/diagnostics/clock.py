"""Wall-clock offset estimation for cross-rank trace alignment.

Timeline shards are stamped with each host's own clocks; on a pod the
hosts' wall clocks can disagree by far more than a collective takes, so
merging shards raw would show rank 3 "responding" before rank 0 asked.
Each shard therefore records an estimated offset to the coordinator's
wall clock, measured by piggybacking on the collective plane that init
just brought up: after a barrier releases every rank ~simultaneously,
all ranks sample ``time.time()`` and allgather the samples; my offset is
the median over a few rounds of ``my_sample - rank0_sample``.  The
barrier bounds the sampling skew to one negotiation round-trip (ms),
while real clock skew on unsynchronized hosts is seconds — good enough
to line tracks up, and free of any extra service.

``HVD_TPU_CLOCK_OFFSET_S`` overrides the estimate (tests inject known
skew; operators can pin a value on NTP-disciplined fleets).
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple


def wall_monotonic_pair() -> Tuple[float, float]:
    """(wall seconds, monotonic seconds) sampled back-to-back — the
    anchor pair shard metadata embeds so monotonic event timestamps can
    be mapped onto the wall clock."""
    return time.time(), time.monotonic()


def estimate_wall_offset(backend=None, rounds: int = 5) -> float:
    """Estimated ``my_wall - coordinator_wall`` in seconds (0.0 when it
    cannot be measured: single process, no backend, or any failure —
    alignment degrades gracefully to raw clocks)."""
    forced = os.environ.get("HVD_TPU_CLOCK_OFFSET_S")
    if forced not in (None, ""):
        try:
            return float(forced)
        except ValueError:
            pass
    if backend is None or getattr(backend, "size", 1) <= 1:
        return 0.0
    try:
        return _measure(backend, rounds)
    except Exception:
        return 0.0


def _measure(backend, rounds: int) -> float:
    import numpy as np
    offsets = []
    for i in range(max(rounds, 1)):
        backend.barrier()  # release is ~simultaneous on every rank
        sample = np.asarray([time.time()], np.float64)
        gathered = backend.allgather_async(
            f"_hvd.clocksync.{i}", sample).wait(30)
        gathered = np.asarray(gathered).reshape(-1)
        if gathered.size < 2:
            return 0.0
        offsets.append(float(sample[0] - gathered[0]))
    offsets.sort()
    return offsets[len(offsets) // 2]
