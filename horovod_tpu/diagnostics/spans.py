"""Per-collective span ids: the cross-rank correlation key.

A span id is ``"<tensor_name>#<occurrence>"`` where the occurrence is a
per-name enqueue counter.  Because negotiation already requires every
rank to submit the same tensor names in a compatible order (the
coordinator matches announcements BY NAME — reference
``controller.cc ComputeResponseList``), each rank computing the counter
independently yields the SAME span id for the same logical collective —
no extra wire traffic.  The C++ core's timeline derives spans the same
way (``cpp/timeline.cc Timeline::NoteEnqueue``), so the merged
cross-rank trace correlates host shards and the engine trace without a
handshake.

The active span is tracked per-thread so log lines emitted inside a
traced collective can carry it (``common/logging.py`` appends it to the
record format) and be joined against the merged trace.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Iterator, Optional

_counts: Dict[str, int] = {}
_lock = threading.Lock()
_active = threading.local()


def next_span(name: str) -> str:
    """Allocate the span id for this enqueue of ``name`` (per-name
    occurrence counter; deterministic across SPMD ranks)."""
    with _lock:
        # auto-named tensors mint fresh names forever: bound the map.
        # Every rank sees the same name sequence (negotiation requires
        # it), so the reset lands on the same enqueue on every rank and
        # ids stay aligned (cpp/timeline.cc applies the same bound).
        if len(_counts) >= 65536:
            _counts.clear()
        seq = _counts.get(name, 0) + 1
        _counts[name] = seq
    return f"{name}#{seq}"


def current_span() -> Optional[str]:
    """Span id of the collective being traced on THIS thread, if any."""
    return getattr(_active, "span", None)


def set_active(span: Optional[str]) -> None:
    _active.span = span


@contextlib.contextmanager
def active_span(span: str) -> Iterator[str]:
    """Scope ``span`` as the thread's active span (for log joining)."""
    prev = current_span()
    _active.span = span
    try:
        yield span
    finally:
        _active.span = prev


def reset() -> None:
    """Drop all per-name counters (tests and elastic re-init: a new
    world negotiates from a clean slate, so spans must too)."""
    with _lock:
        _counts.clear()
