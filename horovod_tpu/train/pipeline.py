"""Composed DP x PP train-step factory: pipelined training whose dp
gradient traffic goes through the bucketed overlap engine.

``make_pipeline_train_step`` is the pipeline analog of
:func:`horovod_tpu.train.overlap.make_overlap_train_step` — and
degenerates INTO it when the plan has ``pp == 1``, so one factory serves
the whole dp x pp plane. The model contract is layer-major (the layout
the flagship transformer's scanned blocks already use):

* ``params``: a pytree whose every leaf has leading dim ``n_layers``
  (layer ``i``'s parameters are ``tree_map(lambda p: p[i], params)``).
* ``layer_fn(layer_params, x) -> x`` applies ONE layer (activation
  shape preserved — the pipeline carry is a single array).
* ``loss_fn(y, targets) -> scalar`` consumes the last layer's output.

Layer-major is what makes (pp, virtual_stages) SEARCHABLE axes: the
same params restack into any ``pp x v`` split by reshaping the leading
dim, so the autotuner can score ``dp8/pp1`` against ``dp2xpp4/1f1b/m8``
against ``dp4xpp2/interleaved`` without touching the model
(docs/PERF.md "Pipeline parallelism").

Inside the step, stage gradients leave the pipeline scan through
:func:`~horovod_tpu.train.overlap.bucketed_grad_sync` over the dp axis
— byte-budgeted buckets, psum/ring/hierarchical algorithms, int8/fp8
error-feedback codecs, and the overlap telemetry all apply — instead of
the dense inline ``lax.pmean`` the island schedules used
(``dp_sync="dense"`` keeps the exact-parity fallback). Parameters and
optimizer state live pp-SHARDED along the layer dim (each pipeline rank
holds only its stages — the door to models too big for one chip), and
the (elementwise) optimizer applies inside ``shard_map`` on the local
shard with buffer donation, like ``make_overlap_train_step``.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from horovod_tpu.common.logging import get_logger
from horovod_tpu.ops.reduce_op import Average, ReduceOp

log = get_logger()


def _pipeline_metrics(plan) -> None:
    """Land the locked parallelism layout on /metrics
    (docs/OBSERVABILITY.md "Pipeline metrics")."""
    try:
        from horovod_tpu.metrics.registry import default_registry
        from horovod_tpu.parallel.plan import SCHEDULES
        reg = default_registry()
        reg.gauge("hvd_pipeline_stages",
                  help="pipeline depth (pp mesh axis) of the active "
                       "train step").set(float(plan.pp))
        reg.gauge("hvd_pipeline_virtual_stages",
                  help="virtual stage chunks per device (interleaved "
                       "schedule)").set(float(plan.virtual_stages))
        reg.gauge("hvd_pipeline_microbatches",
                  help="microbatches per step of the active pipeline "
                       "plan").set(float(plan.n_microbatches))
        reg.gauge("hvd_pipeline_bubble_fraction",
                  help="analytic fill+drain bubble fraction of the "
                       "active schedule").set(plan.bubble_fraction())
        # exactly one schedule series reads 1 (re-lock zeroes the rest)
        for s in SCHEDULES:
            reg.gauge("hvd_pipeline_schedule",
                      help="active pipeline schedule (1 on the locked "
                           "schedule's series)",
                      labels={"schedule": s}).set(
                1.0 if s == plan.schedule else 0.0)
    except Exception:   # metrics are telemetry, never a step failure
        log.debug("pipeline metrics unavailable", exc_info=True)


def record_measured_bubble(measured: float) -> None:
    """Land the MEASURED bubble fraction of the active pipeline step on
    /metrics next to the analytic one (docs/OBSERVABILITY.md "Pipeline
    metrics").  Derivation is the overlap_bench attribution pattern
    (``benchmarks/overlap_bench.py``): time the same model + global
    batch at ``pp=1`` — per-device compute is identical
    (``n_layers·M·rows/pp`` either way) with zero pipeline
    dependencies — and ``1 − t_compute / t_pipelined`` is the fraction
    of the pipelined step the devices spent NOT computing.  The
    analytic gauge says what the schedule should cost; this one says
    what it did — drift between them is remat/comm overhead the tick
    model cannot see (``ci/check_bench.py --pipeline`` prints both)."""
    try:
        from horovod_tpu.metrics.registry import default_registry
        default_registry().gauge(
            "hvd_pipeline_bubble_fraction_measured",
            help="measured bubble fraction of the active pipeline "
                 "step: 1 - compute-only (pp=1) step time / pipelined "
                 "step time").set(
            max(0.0, min(1.0, float(measured))))
    except Exception:
        log.debug("measured-bubble gauge unavailable", exc_info=True)


def stage_layout_permutation(n_layers: int, pp: int,
                             virtual_stages: int = 1) -> np.ndarray:
    """Natural-layer-order -> storage-order permutation for a pp x v
    split. Storage is device-major (device d's chunks contiguous) so a
    plain contiguous shard over ``pp`` hands every pipeline rank its own
    stages; for ``v == 1`` this is the identity. ``perm[i]`` is the
    natural index stored at slot ``i``."""
    if n_layers % (pp * virtual_stages) != 0:
        raise ValueError(
            f"{n_layers} layers not divisible into pp={pp} x "
            f"v={virtual_stages} stages")
    per_stage = n_layers // (pp * virtual_stages)
    order = []
    for d in range(pp):
        for j in range(virtual_stages):
            q = j * pp + d        # semantic stage of chunk j on device d
            order.extend(range(q * per_stage, (q + 1) * per_stage))
    return np.asarray(order, np.int64)


class PipelineTrainStep:
    """Callable ``step(params, opt_state, batch) -> (params, opt_state,
    loss)`` with the plan's layout captured.

    ``prepare_params`` / ``restore_params`` convert between the model's
    natural layer order and the plan's device-major storage order
    (identity unless the schedule is interleaved) — run ``params``
    through ``prepare_params`` ONCE before ``optimizer.init`` and
    training, and ``restore_params`` before export."""

    def __init__(self, fn_builder: Callable, plan, mesh,
                 perm: np.ndarray) -> None:
        self._fn_builder = fn_builder
        self._fn: Optional[Callable] = None
        self.plan = plan
        self.mesh = mesh
        self._perm = perm
        self._inv = np.argsort(perm)

    def _permute(self, tree, perm):
        import jax
        L = len(perm)
        if np.array_equal(perm, np.arange(L)):
            return tree
        # only layer-major leaves move; optimizer scalars (adam count)
        # and any non-layer state pass through untouched, so this also
        # converts a whole optimizer state tree
        return jax.tree_util.tree_map(
            lambda p: p[perm] if (np.ndim(p) >= 1
                                  and np.shape(p)[0] == L) else p, tree)

    def prepare_params(self, params):
        """Natural layer order -> this plan's device-major storage order
        (identity unless interleaved). Also converts optimizer state."""
        return self._permute(params, self._perm)

    def restore_params(self, params):
        """Storage order back to natural layer order (for export)."""
        return self._permute(params, self._inv)

    def __call__(self, params, opt_state, batch, *extra):
        # *extra: the guard-enabled factory's injection scalars ride
        # through to the compiled body (train/guard.py GuardedStep)
        if self._fn is None:
            self._fn = self._fn_builder(params, opt_state)
        return self._fn(params, opt_state, batch, *extra)

    def __getattr__(self, name):
        # forward to the wrapped step: the pp==1 degenerate path nests
        # an (already guard-wrapped) overlap step INSIDE this shell, and
        # its surface (flush(), observer, guard_spec — train/guard.py)
        # must stay reachable through it
        fn = self.__dict__.get("_fn")
        if fn is None:
            raise AttributeError(name)
        return getattr(fn, name)


def _layer_specs(tree, n_layers: int, axis_name: str):
    """Per-leaf shard_map specs: leaves carrying the layer dim shard
    over ``axis_name``; everything else (optimizer scalars like adam's
    ``count``) replicates."""
    import jax
    from jax.sharding import PartitionSpec as P

    def spec(leaf):
        shape = np.shape(leaf)
        return P(axis_name) if (len(shape) >= 1 and shape[0] == n_layers) \
            else P()
    return jax.tree_util.tree_map(spec, tree)


def make_pipeline_train_step(layer_fn: Callable, loss_fn: Callable,
                             optimizer, plan=None, *,
                             n_layers: int,
                             mesh=None,
                             devices: Optional[Sequence] = None,
                             schedule: str = "1f1b",
                             pp: Optional[int] = None,
                             n_micro: int = 1,
                             virtual_stages: int = 1,
                             op: ReduceOp = Average,
                             dp_sync: str = "bucketed",
                             bucket_bytes: Optional[int] = None,
                             compression=None,
                             algorithm: Optional[str] = None,
                             topology=None,
                             small_floor: Optional[int] = None,
                             donate: bool = True,
                             autotune=None,
                             guard=None) -> PipelineTrainStep:
    """Build the composed DP x PP train step for a layer-major model
    (module docstring for the contract).

    Either pass a bound :class:`~horovod_tpu.parallel.plan.ParallelPlan`
    (``plan=``, optionally with its nested comms plan) or the individual
    knobs (``schedule``/``pp``/``n_micro``/``virtual_stages`` plus the
    ``bucketed_grad_sync`` communication kwargs). ``pp == 1`` (or a
    1-device world) degenerates into
    :func:`~horovod_tpu.train.overlap.make_overlap_train_step` — same
    signature, same microbatch-accumulation semantics, bucket overlap
    engine and all. ``autotune`` (or ``HVD_TPU_AUTOTUNE_MESH=1``) hands
    (pp, n_microbatches, schedule) AND the communication knobs to the
    parallel-plan search (docs/PERF.md "Autotuning"); an explicit
    ``plan=`` pins the layout with zero search.

    ``dp_sync="bucketed"`` (default) routes stage gradients through
    :func:`~horovod_tpu.train.overlap.bucketed_grad_sync` on the dp
    axis; ``"dense"`` is the exact-parity dense-``pmean`` fallback.
    Quantized codecs change wire numerics (error feedback recommended at
    the optimizer level; trajectory-level parity is what the tests
    hold). The optimizer applies per pipeline rank on its own stage
    shard — elementwise transforms (sgd/adam/adamw/...) only; a
    cross-parameter transform (e.g. global-norm clipping) would see one
    rank's stages.
    """
    import jax

    from horovod_tpu.parallel.mesh import dp_pp_mesh, mesh_axis_size
    from horovod_tpu.parallel.plan import ParallelPlan

    if autotune is None:
        from horovod_tpu.common.config import get_config
        autotune = get_config().autotune_mesh or None
    if autotune and plan is None:
        from horovod_tpu.train.autotune import make_parallel_train_step
        return make_parallel_train_step(
            layer_fn, loss_fn, optimizer, n_layers=n_layers,
            devices=devices, autotune=autotune, op=op, donate=donate,
            guard=guard)

    if plan is None:
        if mesh is not None:
            world = int(np.prod(list(mesh.shape.values())))
            pp_ = pp if pp is not None else mesh_axis_size(mesh, "pp")
        else:
            world = len(list(devices)) if devices is not None \
                else jax.device_count()
            pp_ = pp if pp is not None else 1
        if world % pp_ != 0:
            raise ValueError(
                f"pp={pp_} does not divide the {world}-device world")
        comms = None
        if bucket_bytes is not None or algorithm is not None \
                or compression is not None or small_floor is not None:
            from horovod_tpu.train.autotune import Plan
            from horovod_tpu.train.autotune import _codec_name
            from horovod_tpu.train.buckets import resolve_bucket_bytes
            from horovod_tpu.train.overlap import resolve_small_floor
            comms = Plan(
                bucket_bytes=resolve_bucket_bytes(bucket_bytes),
                algorithm=algorithm or "psum",
                codec=_codec_name(compression),
                small_floor=resolve_small_floor(small_floor))
        plan = ParallelPlan(
            dp=max(1, world // pp_), pp=pp_,
            schedule=schedule if pp_ > 1 else "1f1b",
            n_microbatches=n_micro,
            virtual_stages=virtual_stages
            if (pp_ > 1 and schedule == "interleaved") else 1,
            comms=comms)
    if mesh is None:
        mesh = plan.build_mesh(devices=devices)
    plan.validate_for(int(np.prod(list(mesh.shape.values()))),
                      n_layers=n_layers)
    if mesh_axis_size(mesh, "pp") != plan.pp:
        raise ValueError(
            f"mesh pp axis is {mesh_axis_size(mesh, 'pp')} but the plan "
            f"wants pp={plan.pp}; build the mesh with dp_pp_mesh or "
            f"plan.build_mesh()")

    # the quantizer instance for the dp hop, from explicit kwarg or the
    # plan's nested comms codec
    if compression is None and plan.comms is not None:
        compression = plan.comms.resolve_codec()
    comm_kwargs = dict(
        bucket_bytes=plan.comms.bucket_bytes if plan.comms else bucket_bytes,
        compression=compression,
        algorithm=(plan.comms.algorithm if plan.comms else algorithm),
        topology=topology,
        small_floor=(plan.comms.small_floor if plan.comms else small_floor))

    _pipeline_metrics(plan)

    if plan.pp == 1:
        from jax import lax

        from horovod_tpu.train.overlap import make_overlap_train_step

        def full_loss(params, batch):
            x, tgt = batch

            def body(h, lp):
                return layer_fn(lp, h), None
            y, _ = lax.scan(body, x, params)
            return loss_fn(y, tgt)

        inner = make_overlap_train_step(
            full_loss, optimizer, mesh, "dp",
            n_micro=plan.n_microbatches, op=op, donate=donate,
            autotune=False, guard=guard, **comm_kwargs)
        # the inner step is already guard-wrapped (or plain, guard off):
        # the pipeline shell only carries the plan/permutation surface.
        # Bind it EAGERLY — the guard surface (flush()/observer) must be
        # reachable through __getattr__ before the first call too.
        step = PipelineTrainStep(lambda *_: inner, plan, mesh,
                                 np.arange(n_layers))
        step._fn = inner
        return step

    perm = stage_layout_permutation(n_layers, plan.pp, plan.virtual_stages)

    from horovod_tpu.train import guard as guard_mod
    gspec = guard_mod.resolve_spec(guard)
    from horovod_tpu import chaos as _chaos
    inject_armed = gspec.enabled and _chaos.grad_rules_armed()

    def fn_builder(params_ex, opt_state_ex):
        import optax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.parallel.pipeline import (pipeline_1f1b_spmd,
                                                   pipeline_spmd)
        from horovod_tpu.parallel.plan import compile_step_with_plan
        from horovod_tpu.train.overlap import bucketed_grad_sync

        S = plan.pp
        M = plan.n_microbatches
        v = plan.virtual_stages
        dp_live = mesh_axis_size(mesh, "dp") > 1

        def stage_scan(stage_params, x):
            def body(h, lp):
                return layer_fn(lp, h), None
            y, _ = lax.scan(body, x, stage_params)
            return y

        def dp_reduce(grads):
            if not dp_live:
                return grads
            if dp_sync == "dense":
                return jax.tree_util.tree_map(
                    lambda g: lax.pmean(g, "dp"), grads)
            return bucketed_grad_sync(grads, "dp", op=op, **comm_kwargs)

        def body(params, opt_state, batch, *inj):
            x, tgt = batch
            xm = x.reshape((M, x.shape[0] // M) + x.shape[1:])
            tm = tgt.reshape((M, tgt.shape[0] // M) + tgt.shape[1:])
            if plan.schedule == "interleaved":
                from horovod_tpu.parallel.pipeline import (
                    pipeline_interleaved_spmd)
                per_chunk = n_layers // (S * v)
                chunks = jax.tree_util.tree_map(
                    lambda p: p.reshape((v, per_chunk) + p.shape[1:]),
                    params)
                loss, grads = pipeline_interleaved_spmd(
                    stage_scan, loss_fn, chunks, xm, tm, v, "pp")
                grads = jax.tree_util.tree_map(
                    lambda g: g.reshape((v * per_chunk,) + g.shape[2:]),
                    grads)
            elif plan.schedule == "1f1b":
                loss, grads = pipeline_1f1b_spmd(
                    stage_scan,
                    loss_fn,
                    jax.tree_util.tree_map(lambda p: p[None], params),
                    xm, tm, "pp")
            else:  # gpipe-by-autodiff
                def total(pl):
                    ym = pipeline_spmd(
                        stage_scan,
                        jax.tree_util.tree_map(lambda p: p[None], pl),
                        xm, "pp")
                    return jax.vmap(loss_fn)(ym, tm).mean()
                loss, grads = jax.value_and_grad(total)(params)
            if plan.schedule != "gpipe":
                # the 1F1B-family schedules accumulate gradient SUMS
                # over microbatches; gpipe's vmap-mean carries the 1/M
                grads = jax.tree_util.tree_map(lambda g: g / M, grads)
            grads = dp_reduce(grads)
            if dp_live:
                loss = lax.pmean(loss, "dp")
            if not gspec.enabled:
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss
            if inject_armed:
                grads = guard_mod.apply_injection(grads, inj[0])
            # the verdict scalar is psum'd over pp: stage grads are
            # pp-SHARDED, and every stage must reach the same
            # skip/apply decision (docs/TROUBLESHOOTING.md)
            params, opt_state, ok = guard_mod.guarded_apply(
                optimizer, grads, opt_state, params, gspec,
                pp_axis="pp")
            return params, opt_state, loss, ok

        # distinct per-plan name: the compile watcher labels compiles by
        # function name, and an autotune search compiling one `body` per
        # candidate would read as a recompile storm (and burn an anomaly
        # capture) when it is really N different programs
        body.__name__ = f"pipeline_body[{plan.key}]"
        p_specs = _layer_specs(params_ex, n_layers, "pp")
        o_specs = _layer_specs(opt_state_ex, n_layers, "pp")
        batch_spec = P("dp")
        in_specs = (p_specs, o_specs, (batch_spec, batch_spec))
        out_specs = (p_specs, o_specs, P())
        if gspec.enabled:
            in_specs = in_specs + (P(),)       # the injection scalars
            out_specs = out_specs + (P(),)     # the guard verdict
        return compile_step_with_plan(
            body, mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            donate_argnums=(0, 1) if donate else ())

    step = PipelineTrainStep(fn_builder, plan, mesh, perm)
    if gspec.enabled:
        return guard_mod.GuardedStep(step, gspec, inject=inject_armed)
    return step
