"""Numeric guardrails + cross-replica canary: the data-plane integrity
layer of the train step (docs/TROUBLESHOOTING.md "My loss went NaN / my
replicas disagree").

Three pieces, one file, because they share the threat model — silently
wrong math poisoning a run long before anyone looks:

* **Grad guard** (:func:`guarded_apply`): a jit-friendly finiteness +
  global-norm check fused into the train-step factories
  (``make_overlap_train_step`` / ``make_pipeline_train_step``).  One
  scalar — the gradient sum-of-squares, computed on the POST-sync
  gradients, which are replicated across dp by construction — decides
  the step: non-finite (or over ``HVD_TPU_GUARD_MAX_NORM``) means the
  update is zeroed and the optimizer state preserved (the skip-step
  policy), so one poisoned batch costs one step, not the run.  No added
  collective round on the dp axis; the pipeline path psums the one
  scalar over pp so every stage agrees.  Sum-of-squares overflow to inf
  counts as a spike — that is the gradient explosion the guard exists
  for.
* **Skip accounting** (:class:`GuardObserver`): every skipped step
  counts ``hvd_guard_skipped_steps_total`` and lands a ``guard_skip``
  flight event; ``HVD_TPU_GUARD_ESCALATE`` consecutive skips escalate
  into a ``grad_nonfinite`` anomaly finding — the autopilot's
  ``rollback_restore`` policy subscribes to it (a persistently poisoned
  run should restore the last durable checkpoint, not keep committing a
  corrupt optimizer state forward).  Observation is one step deferred
  (step k's verdict is read while step k+1 is in flight) so the guard
  never forces a device sync onto the dispatch pipeline.
* **Replica canary** (:class:`ReplicaCanary`): every
  ``HVD_TPU_CANARY_EVERY`` steps, allgather a cheap digest of a fixed
  parameter slice — bit-identical across DP replicas by construction —
  and flag the odd rank out as a ``replica_divergence`` finding.  This
  catches compute SDC (a device producing silently-wrong math) that the
  wire CRC (``HVD_TPU_WIRE_CHECKSUM``, cpp/transport.cc) cannot: the
  bytes traveled intact, they were wrong at birth.  The autopilot's
  ``quarantine_rank`` policy subscribes to it.

The chaos ``grad`` seam (docs/CHAOS.md) drives all of it
deterministically: when a plan arms grad rules for this rank, the
factories compile an injection seam that corrupts the step's gradients
in-graph (nan / inf / ``factor``-scale) — the injection code travels as
DATA, so a firing window never recompiles the step.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any, List, Optional, Tuple

import numpy as np

from horovod_tpu.common.config import env_bool, env_float, env_int
from horovod_tpu.common.logging import get_logger

log = get_logger()

#: elements digested per leaf (a FIXED parameter slice — cheap, layout-
#: independent, and enough that real divergence cannot hide: a replica
#: whose math went wrong diverges everywhere, not in one element)
DIGEST_ELEMS_PER_LEAF = 256


# -- spec ---------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class GuardSpec:
    """Resolved guard configuration (env defaults, see docs/KNOBS.md)."""
    enabled: bool = True
    max_norm: float = 0.0        # 0 = finiteness only, no norm cap
    escalate_after: int = 3      # consecutive skips -> grad_nonfinite

    @staticmethod
    def from_env() -> "GuardSpec":
        return GuardSpec(
            enabled=env_bool("GUARD", True),
            max_norm=max(0.0, env_float("GUARD_MAX_NORM", 0.0)),
            escalate_after=max(1, env_int("GUARD_ESCALATE", 3)))


def resolve_spec(guard) -> GuardSpec:
    """The factories' ``guard=`` seam: ``None`` reads env, ``False``
    disables, ``True`` is the env-tuned default, a :class:`GuardSpec`
    pins everything."""
    if isinstance(guard, GuardSpec):
        return guard
    if guard is None:
        return GuardSpec.from_env()
    if guard is False:
        return GuardSpec(enabled=False)
    if guard is True:
        spec = GuardSpec.from_env()
        return dataclasses.replace(spec, enabled=True)
    raise TypeError(f"guard must be None/bool/GuardSpec, got {guard!r}")


# -- the in-graph pieces ------------------------------------------------------

def apply_injection(grads, inject):
    """Chaos ``grad`` seam, in-graph: ``inject`` is a length-2 float32
    vector ``[code, factor]`` (:data:`horovod_tpu.chaos.GRAD_CODES`).
    Code 0 leaves the gradients numerically unchanged; 1 adds nan,
    2 adds inf, 3 multiplies by ``factor``.  Data-dependent on purpose:
    the same compiled step serves clean and fault-window steps."""
    import jax
    import jax.numpy as jnp

    code = inject[0]
    add = jnp.where(code == 1, jnp.float32(jnp.nan),
                    jnp.where(code == 2, jnp.float32(jnp.inf),
                              jnp.float32(0.0)))
    mul = jnp.where(code == 3, inject[1], jnp.float32(1.0))
    return jax.tree_util.tree_map(
        lambda g: g * mul.astype(g.dtype) + add.astype(g.dtype), grads)


def grads_ok(grads, spec: GuardSpec, pp_axis: Optional[str] = None):
    """The one-scalar verdict: sum of squared gradients (float32) must
    be finite, and under ``max_norm**2`` when a norm cap is set.  Call
    on POST-dp-sync gradients (replicated across dp — no collective
    needed); ``pp_axis`` psums the scalar across pipeline stages so
    every stage reaches the same verdict."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    leaves = jax.tree_util.tree_leaves(grads)
    sq = jnp.float32(0.0)
    for leaf in leaves:
        sq = sq + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    if pp_axis is not None:
        sq = lax.psum(sq, pp_axis)
    ok = jnp.isfinite(sq)
    if spec.max_norm > 0:
        ok = jnp.logical_and(ok, sq <= jnp.float32(spec.max_norm) ** 2)
    return ok


def guarded_apply(optimizer, grads, opt_state, params, spec: GuardSpec,
                  pp_axis: Optional[str] = None):
    """Skip-step optimizer apply: returns ``(params, opt_state, ok)``
    where a failed verdict yields the UNCHANGED params and optimizer
    state (a zeroed update that also keeps adam's moments clean of the
    poisoned gradients — the optimizer state is preserved, not advanced
    on garbage)."""
    import jax
    import jax.numpy as jnp
    import optax

    ok = grads_ok(grads, spec, pp_axis=pp_axis)
    updates, new_opt = optimizer.update(grads, opt_state, params)
    new_params = optax.apply_updates(params, updates)

    def sel(new, old):
        return jnp.where(ok, new, old)

    return (jax.tree_util.tree_map(sel, new_params, params),
            jax.tree_util.tree_map(sel, new_opt, opt_state),
            ok)


# -- host-side skip accounting ------------------------------------------------

class GuardObserver:
    """Counts skipped steps and escalates persistent non-finiteness.

    Fed by :class:`GuardedStep` with a ONE-STEP delay (step k's ``ok``
    scalar is read at step k+1, when it is certainly resolved) so the
    guard never stalls dispatch.  ``flush()`` drains the pending
    verdict — tests and end-of-run paths call it."""

    def __init__(self, spec: GuardSpec) -> None:
        self.spec = spec
        self.skipped = 0
        self.consecutive = 0
        self._counter = None

    def observe(self, step: int, ok: bool) -> None:
        if ok:
            self.consecutive = 0
            return
        self.skipped += 1
        self.consecutive += 1
        try:
            if self._counter is None:
                from horovod_tpu.metrics.registry import default_registry
                self._counter = default_registry().counter(
                    "hvd_guard_skipped_steps_total",
                    help="train steps skipped by the numeric guardrail "
                         "(non-finite or over-norm gradients; update "
                         "zeroed, optimizer state preserved)")
            self._counter.inc()
        except Exception:
            pass
        try:
            from horovod_tpu.diagnostics.flight_recorder import record_event
            record_event("guard_skip", step=int(step),
                         consecutive=self.consecutive)
        except Exception:
            pass
        log.warning(
            "guard: skipped step %d (non-finite or over-norm gradients; "
            "%d consecutive)", step, self.consecutive)
        if self.consecutive % self.spec.escalate_after == 0:
            # every Nth consecutive skip re-reports; the autopilot's
            # cooldown gate dedups, and a run that stays poisoned keeps
            # saying so instead of going quiet after one finding
            try:
                from horovod_tpu.metrics.anomaly import report_finding
                report_finding("grad_nonfinite", step=int(step),
                               consecutive=self.consecutive)
            except Exception:
                pass


# -- replica canary -----------------------------------------------------------

def param_digest(tree, elems_per_leaf: int = DIGEST_ELEMS_PER_LEAF) -> int:
    """Deterministic CRC32 digest of a fixed slice of every leaf (the
    first ``elems_per_leaf`` elements of its flattened value), chained
    in tree-flatten order.  Mesh-layout invariant: ``np.asarray`` on a
    (fully addressable) sharded ``jax.Array`` yields the logical global
    value, so the same parameters digest identically on dp8 and
    dp2xsp2xtp2.  Leaves this process cannot address whole (true
    multi-controller shards) are skipped — the canary compares
    DP-replicated state."""
    import jax

    crc = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        try:
            # slice ON DEVICE first: only elems_per_leaf elements ever
            # cross device->host, not the whole leaf — the digest must
            # stay cheap on billion-parameter trees
            flat = np.asarray(leaf.reshape(-1)[:elems_per_leaf])
        except Exception:
            try:
                flat = np.asarray(leaf).reshape(-1)[:elems_per_leaf]
            except Exception:
                continue  # not fully addressable / not array-like
        flat = np.ascontiguousarray(flat)
        crc = zlib.crc32(flat.tobytes(), crc)
        crc = zlib.crc32(str(flat.dtype).encode(), crc)
    return crc & 0x7FFFFFFF


def divergent_ranks(digests) -> List[int]:
    """Majority vote over per-rank digests: ranks whose digest differs
    from the STRICT-majority value are the odd ones out.  No strict
    majority (a 50/50 split, or everyone different) attributes nothing
    — flagging half the fleet on a tie would be worse than silence."""
    values = [int(d) for d in digests]
    if len(values) < 2:
        return []
    counts: dict = {}
    for v in values:
        counts[v] = counts.get(v, 0) + 1
    modal, n = max(counts.items(), key=lambda kv: kv[1])
    if n * 2 <= len(values):
        return []
    return [r for r, v in enumerate(values) if v != modal]


class ReplicaCanary:
    """Cross-replica divergence detector over the eager allgather.

    ``check(step, tree)`` digests the caller's (DP-replicated) state,
    allgathers one int64 per rank, and reports a ``replica_divergence``
    anomaly finding naming each odd rank out.  Wired into
    :class:`GuardedStep` every ``HVD_TPU_CANARY_EVERY`` steps (0 = off,
    the default — the digest allgather is cheap but it IS a collective);
    custom loops call ``check`` directly."""

    def __init__(self, every: int,
                 elems_per_leaf: int = DIGEST_ELEMS_PER_LEAF) -> None:
        self.every = int(every)
        self.elems_per_leaf = elems_per_leaf

    @staticmethod
    def from_env() -> Optional["ReplicaCanary"]:
        every = env_int("CANARY_EVERY", 0)
        return ReplicaCanary(every) if every > 0 else None

    def maybe_check(self, step: int, tree) -> List[dict]:
        if self.every <= 0 or step <= 0 or step % self.every != 0:
            return []
        return self.check(step, tree)

    def check(self, step: int, tree) -> List[dict]:
        """Returns the findings reported (usually []).  A no-op unless
        hvd is initialized with a multi-process world — the canary
        compares REPLICAS, and a single process holds only one."""
        try:
            from horovod_tpu.common.basics import is_initialized, rank, size
            if not is_initialized() or size() < 2:
                return []
            world = size()
            own_rank = rank()
        except Exception:
            return []
        digest = param_digest(tree, self.elems_per_leaf)
        try:
            from horovod_tpu.ops.collectives import allgather
            gathered = np.asarray(allgather(
                np.array([digest], np.int64), name="hvd.canary.digest"))
        except Exception:
            log.warning("canary: digest allgather failed", exc_info=True)
            raise
        try:
            from horovod_tpu.metrics.registry import default_registry
            default_registry().counter(
                "hvd_canary_checks_total",
                help="cross-replica canary digest comparisons run").inc()
        except Exception:
            pass
        digests = [int(d) for d in gathered.reshape(-1)[:world]]
        odd = divergent_ranks(digests)
        findings = []
        if not odd and len(set(digests)) > 1:
            # replicas DISAGREE but no strict majority can convict a
            # rank (a 2-replica world, a 50/50 split, everyone
            # different): quarantine has no target, but silence here
            # would read as a green canary — count it and say so
            try:
                from horovod_tpu.metrics.registry import default_registry
                default_registry().counter(
                    "hvd_canary_divergence_total",
                    help="canary checks that flagged a divergent "
                         "replica").inc()
            except Exception:
                pass
            try:
                from horovod_tpu.diagnostics.flight_recorder import (
                    record_event)
                record_event("canary_mismatch", step=int(step),
                             digests=[hex(d) for d in digests])
            except Exception:
                pass
            log.error(
                "canary: replica digests DISAGREE at step %d with no "
                "attributable majority (world %d: %s) — data corruption "
                "somewhere, but no rank can be convicted; compare the "
                "replicas' state by hand (docs/TROUBLESHOOTING.md)",
                step, world, [hex(d) for d in digests])
        for r in odd:
            try:
                from horovod_tpu.metrics.registry import default_registry
                default_registry().counter(
                    "hvd_canary_divergence_total",
                    help="canary checks that flagged a divergent "
                         "replica").inc()
            except Exception:
                pass
            log.error(
                "canary: replica DIVERGENCE at step %d — rank %d digest "
                "%#x disagrees with the majority (world %d, own rank "
                "%d); silent data corruption upstream of the wire",
                step, r, int(gathered[r]), world, own_rank)
            try:
                from horovod_tpu.metrics.anomaly import report_finding
                f = report_finding(
                    "replica_divergence", rank=int(r), step=int(step),
                    digest=int(gathered[r]),
                    majority=int(
                        [d for i, d in enumerate(gathered.reshape(-1))
                         if i not in odd][0]),
                    world=int(world))
                if f:
                    findings.append(f)
            except Exception:
                pass
        return findings


# -- the step wrapper ---------------------------------------------------------

class GuardedStep:
    """Callable wrapper the guard-enabled factories return: same
    ``step(params, opt_state, batch) -> (params, opt_state, loss)``
    surface as before, with the compiled function's 4th output (the
    guard verdict) stripped, observed one step late, and the canary run
    every ``HVD_TPU_CANARY_EVERY`` steps.  Attribute access forwards to
    the wrapped step (``.lower``, ``.plan``, ``.prepare_params``, ...)
    so autotune/bench/pipeline callers keep working."""

    def __init__(self, fn, spec: GuardSpec, inject: bool = False,
                 observer: Optional[GuardObserver] = None,
                 canary: Optional[ReplicaCanary] = "env") -> None:
        self._fn = fn
        self.guard_spec = spec
        self._inject = inject
        self.observer = observer or GuardObserver(spec)
        self.canary = ReplicaCanary.from_env() if canary == "env" \
            else canary
        self._step = 0
        self._pending: Optional[Tuple[int, Any]] = None
        self._zero_inj = None  # cached clean-injection device array

    def __call__(self, params, opt_state, batch):
        import jax.numpy as jnp

        self.flush()
        code, factor = (0, 0.0)
        if self._inject:
            from horovod_tpu import chaos
            code, factor = chaos.grad_injection(self._step)
        if code == 0:
            # the production path: one constant device array, built
            # once — no per-step host allocation/transfer
            if self._zero_inj is None:
                self._zero_inj = jnp.zeros((2,), jnp.float32)
            inj = self._zero_inj
        else:
            inj = jnp.asarray(np.array([code, factor], np.float32))
        params, opt_state, loss, ok = self._fn(params, opt_state, batch,
                                               inj)
        self._pending = (self._step, ok)
        if self.canary is not None:
            self.canary.maybe_check(self._step, params)
        self._step += 1
        return params, opt_state, loss

    def flush(self) -> None:
        """Resolve the deferred verdict of the previous step (reads one
        device scalar; it completed alongside that step's loss)."""
        if self._pending is not None:
            step, ok = self._pending
            self._pending = None
            try:
                self.observer.observe(step, bool(np.asarray(ok)))
            except Exception:
                log.debug("guard verdict readback failed", exc_info=True)

    def __getattr__(self, name):
        return getattr(self._fn, name)
