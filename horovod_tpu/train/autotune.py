"""Mesh-path communication autotuner: online plan search with a
persistent tuning cache.

The eager TCP core closes its tuning loop in C++ (``cpp/core.cc``
ParameterManager driving the GP/EI optimizer in ``cpp/bayes_opt.cc``
over fusion bytes / cycle time / hierarchical / cache). The traced mesh
path — where every real TPU step runs — had no analog: bucket bytes,
collective algorithm and codec were hand-set knobs. This module closes
that loop:

* :class:`Plan` — one point in the discrete search space:
  ``bucket_bytes × algorithm {psum, ring, hier} × codec {none, int8,
  fp8} × small-bucket floor``.
* :class:`AutotuneController` — successive halving over candidate
  plans, scored by REAL measured step time (the same wall clock
  ``StepTimer`` feeds the PR-7 time-series ring), bounded by a step
  budget; every trial and the final choice land on ``/metrics``
  (``hvd_autotune_*``), in the flight recorder, and in a CSV trace like
  the C++ core's ``HVD_TPU_AUTOTUNE_LOG``.
* :class:`PlanCache` — the winner is persisted to a JSON cache keyed by
  a fingerprint (grad-tree structure, mesh shape, world size, dtype,
  codec availability), so subsequent runs — including elastic re-meshes
  back to a previously seen world size — start at the tuned config with
  ZERO search trials. Corrupt or stale entries are ignored with a
  warning and retuned, never crash init.
* :func:`make_autotuned_train_step` — the ``autotune=`` seam behind
  :func:`horovod_tpu.train.overlap.make_overlap_train_step`: candidate
  steps are compiled per plan, measured, and the locked winner serves
  steady state with no further timing overhead.

Successive halving (a bandit equivalent of the reference's sample-and-
converge ParameterManager, simpler and deterministic for a discrete
space): every surviving plan gets ``1 + steps_per_trial`` steps per
round — the first is a warmup absorbing compile — then the slower half
is dropped and the per-plan window doubles, until one survivor remains
or the step budget runs out (then the best-scored plan locks).

CPU note: autotune trials must run with the persistent XLA compile
cache DISABLED on the 8-device CPU test mesh (known heap-corruption
signature under warm-cache multi-device dispatch — tests/conftest.py);
nothing here touches the compile-cache config.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from horovod_tpu.common.logging import get_logger

log = get_logger()

PLAN_CACHE_VERSION = 2   # v2: the cache may hold a ParallelPlan (ISSUE 11)
_ALGORITHMS = ("psum", "ring", "hier")
_CODECS = ("none", "int8", "fp8")
DEFAULT_SMALL_FLOOR = 32 * 1024  # latency-path floor candidate (bytes)


# ---------------------------------------------------------------------------
# Plan: one point in the search space
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Plan:
    """One communication configuration for the traced mesh path.

    ``algorithm``: ``psum`` (flat), ``ring`` (chunked ppermute), or
    ``hier`` (topology-aware two-level). ``codec``: ``none``/``int8``/
    ``fp8`` — applied EQuARX-style (gather phase for psum, inter-host
    hop for hier; ring has no codec seam). ``small_floor``: buckets
    under this many bytes take the dense latency path.
    """

    bucket_bytes: int
    algorithm: str = "psum"
    codec: str = "none"
    small_floor: int = 0

    def __post_init__(self):
        if self.algorithm not in _ALGORITHMS:
            raise ValueError(f"unknown algorithm {self.algorithm!r}; "
                             f"expected one of {_ALGORITHMS}")
        if self.codec not in _CODECS:
            raise ValueError(f"unknown codec {self.codec!r}; "
                             f"expected one of {_CODECS}")
        if self.algorithm == "ring" and self.codec != "none":
            raise ValueError("ring has no compression seam")
        if self.bucket_bytes < 1:
            raise ValueError("bucket_bytes must be >= 1")
        if self.small_floor < 0:
            raise ValueError("small_floor must be >= 0")

    @property
    def key(self) -> str:
        """Short human label (CSV / flight / metric labels)."""
        return (f"{self.algorithm}/{self.codec}"
                f"/b{self.bucket_bytes}/f{self.small_floor}")

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Plan":
        return cls(bucket_bytes=int(d["bucket_bytes"]),
                   algorithm=str(d.get("algorithm", "psum")),
                   codec=str(d.get("codec", "none")),
                   small_floor=int(d.get("small_floor", 0)))

    def resolve_codec(self):
        """The codec string as a live Quantizer (None for ``none``)."""
        if self.codec == "none":
            return None
        from horovod_tpu.compression.quantizers import resolve_compressor
        return resolve_compressor(self.codec)

    def step_kwargs(self, topology=None) -> Dict[str, Any]:
        """Keyword arguments for ``make_overlap_train_step`` /
        ``bucketed_grad_sync`` realizing this plan."""
        return dict(bucket_bytes=self.bucket_bytes,
                    algorithm=self.algorithm,
                    compression=self.resolve_codec(),
                    small_floor=self.small_floor,
                    topology=topology)


def _codec_name(compression) -> str:
    if compression is None:
        return "none"
    name = getattr(compression, "name", None)
    if name not in _CODECS:
        raise ValueError(
            f"autotune searches codecs {_CODECS}; got compression="
            f"{compression!r} — drop autotune= or pass a supported codec")
    return name


def _codecs_available() -> Tuple[str, ...]:
    from horovod_tpu.compression.quantizers import fp8_supported
    return ("none", "int8") + (("fp8",) if fp8_supported() else ())


def candidate_plans(topology=None, *, baseline: Optional[Plan] = None,
                    include_fp8: bool = False) -> List[Plan]:
    """The default discrete search space, most-promising-first (the
    controller trims the tail when the step budget can't score them
    all — trimming must drop the speculative end, not the baseline).

    Floor variants are generated only for plans where the floor changes
    semantics (codec or non-flat algorithm); for a dense flat psum the
    latency path IS the plan, so the variant would be a duplicate
    compile.
    """
    from horovod_tpu.train.buckets import resolve_bucket_bytes
    hier_ok = topology is not None and topology.is_hierarchical
    combos: List[Tuple[str, str]] = [("psum", "none"), ("psum", "int8")]
    if hier_ok:
        combos += [("hier", "none"), ("hier", "int8")]
    combos.append(("ring", "none"))
    if include_fp8 and "fp8" in _codecs_available():
        combos.append(("psum", "fp8"))
        if hier_ok:
            combos.append(("hier", "fp8"))
    default_bucket = resolve_bucket_bytes(None)
    buckets = []
    for b in (default_bucket, 1 << 20):
        if b not in buckets:
            buckets.append(b)
    plans: List[Plan] = []
    if baseline is not None:
        plans.append(baseline)
    for bucket in buckets:
        for algo, codec in combos:
            plans.append(Plan(bucket, algo, codec, 0))
    for bucket in buckets:
        for algo, codec in combos:
            if algo == "psum" and codec == "none":
                continue  # floor is a no-op on the dense flat path
            plans.append(Plan(bucket, algo, codec, DEFAULT_SMALL_FLOOR))
    seen, out = set(), []
    for p in plans:
        if p not in seen:
            seen.add(p)
            out.append(p)
    return out


# ---------------------------------------------------------------------------
# Fingerprint + persistent plan cache
# ---------------------------------------------------------------------------

def topology_key(topology, pp: int = 1) -> Dict[str, int]:
    """Canonical mesh/topology component of the cache fingerprint:
    reduction width plus the (hosts × local) structure, WITHOUT the
    mesh axis name — a plan tuned over axis "dp" must warm-start the
    same model reduced over an axis called "data", and the eager
    ``DistributedOptimizer(autotune=True)`` seam (which has no mesh at
    all) must be able to reconstruct the same key from the world size.

    ``pp`` (ISSUE 11): the pipeline dimension of the key. A
    communication plan is tuned UNDER a fixed dp x pp mesh, so its key
    carries that mesh's pp size (default 1). A parallelism-plan search
    passes ``pp=0`` — the sentinel for "the dp x pp split is an axis of
    the search space, keyed by the whole world" — so comm-plan and
    parallel-plan entries for the same model can never shadow each
    other."""
    return {"world": int(topology.world),
            "hosts": int(topology.num_hosts),
            "local": int(topology.local_size),
            "pp": int(pp)}


def plan_fingerprint(tree, mesh_shape: Dict[str, int], world: int,
                     dtype: Optional[str] = None) -> str:
    """Cache key for a tuned plan: sha256 over everything that changes
    which plan wins — gradient-tree structure (leaf shapes + dtypes in
    flatten order), the canonical topology key (:func:`topology_key` —
    pass it as ``mesh_shape``), world size, compute dtype, and codec
    availability (an fp8-capable jax must not reuse a plan tuned
    without fp8 in the space, and vice versa)."""
    import jax
    import numpy as np
    leaves = jax.tree_util.tree_leaves(tree)
    struct = [[list(getattr(l, "shape", np.shape(l))),
               str(getattr(l, "dtype", np.asarray(l).dtype))]
              for l in leaves]
    doc = {
        "v": PLAN_CACHE_VERSION,
        "tree": struct,
        "mesh": sorted((str(k), int(v)) for k, v in mesh_shape.items()),
        "world": int(world),
        "dtype": dtype or (struct[0][1] if struct else "none"),
        "codecs": list(_codecs_available()),
    }
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def resolve_cache_dir(cache_dir: Optional[str] = None) -> str:
    """Effective cache directory: explicit argument >
    ``HVD_TPU_AUTOTUNE_CACHE_DIR``. Empty = persistence disabled (the
    search still runs; it just can't warm-start the next run)."""
    if cache_dir is not None:
        return cache_dir
    from horovod_tpu.common.config import get_config
    return get_config().autotune_cache_dir


class PlanCache:
    """Fingerprint-keyed JSON plan store (one small file per
    fingerprint). Load NEVER raises: a corrupt file (truncated JSON,
    wrong spec version), a fingerprint mismatch (stale rename / copied
    dir) or an unreadable plan logs a warning and returns None — init
    must degrade to a retune, not a crash."""

    def __init__(self, directory: str) -> None:
        self.directory = directory

    def path(self, fingerprint: str) -> str:
        return os.path.join(self.directory,
                            f"plan_{fingerprint[:32]}.json")

    def load(self, fingerprint: str) -> Optional[Plan]:
        if not self.directory:
            return None
        path = self.path(fingerprint)
        try:
            with open(path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            log.warning("autotune plan cache %s unreadable (%s); "
                        "retuning", path, e)
            return None
        try:
            if doc.get("version") != PLAN_CACHE_VERSION:
                log.warning(
                    "autotune plan cache %s has spec version %r (want "
                    "%d); retuning", path, doc.get("version"),
                    PLAN_CACHE_VERSION)
                return None
            if doc.get("fingerprint") != fingerprint:
                log.warning(
                    "autotune plan cache %s fingerprint mismatch "
                    "(stale entry for a different tree/mesh/world); "
                    "retuning", path)
                return None
            # the cache holds either kind of plan: a communication Plan
            # or a full ParallelPlan (dp x pp split + schedule +
            # microbatches + nested comms) — dispatch on the doc
            from horovod_tpu.parallel.plan import plan_from_dict
            return plan_from_dict(doc["plan"])
        except (KeyError, TypeError, ValueError) as e:
            log.warning("autotune plan cache %s carries an invalid "
                        "plan (%s); retuning", path, e)
            return None

    def invalidate(self, fingerprint: Optional[str] = None) -> int:
        """Drop one cached plan (or, with ``fingerprint=None``, every
        plan in the directory); returns how many entries were removed.
        Failures are swallowed — invalidation is hygiene, never an
        error (a missing entry is already the desired state)."""
        if not self.directory:
            return 0
        if fingerprint is not None:
            try:
                os.remove(self.path(fingerprint))
                return 1
            except OSError:
                return 0
        removed = 0
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        for name in names:
            if name.startswith("plan_") and name.endswith(".json"):
                try:
                    os.remove(os.path.join(self.directory, name))
                    removed += 1
                except OSError:
                    pass
        return removed

    def store(self, fingerprint: str, plan: Plan,
              meta: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Atomic write (tmp + rename) so a killed run can't leave a
        truncated entry that poisons the next. Failures log and return
        None — persistence is an optimization, never an error."""
        if not self.directory:
            return None
        doc = {"version": PLAN_CACHE_VERSION,
               "fingerprint": fingerprint,
               "plan": plan.to_dict(),
               "meta": meta or {}}
        path = self.path(fingerprint)
        try:
            os.makedirs(self.directory, exist_ok=True)
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
            return path
        except OSError as e:
            log.warning("autotune plan cache write failed (%s); the "
                        "tuned plan will not survive this process", e)
            return None


def invalidate_plan_cache(cache_dir: Optional[str] = None) -> int:
    """Drop every persisted tuned plan under the resolved cache
    directory (argument > ``HVD_TPU_AUTOTUNE_CACHE_DIR``); returns the
    number of entries removed (0 when persistence is off).  The
    autopilot's ``retune`` remediation calls this on a topology/world
    change (docs/OBSERVABILITY.md "Autopilot"): the cached plans encode
    the OLD world's measured tradeoffs, and the next search must run
    against the world that actually exists."""
    directory = resolve_cache_dir(cache_dir)
    removed = PlanCache(directory).invalidate()
    if removed:
        log.warning("autotune plan cache invalidated: %d entr%s removed "
                    "from %s", removed, "y" if removed == 1 else "ies",
                    directory)
    return removed


# ---------------------------------------------------------------------------
# Online search: successive halving over measured step time
# ---------------------------------------------------------------------------

def _autotune_metrics():
    from horovod_tpu.metrics.registry import default_registry
    return default_registry()


def _record_locked_plan(plan: Plan, best_s: Optional[float],
                        from_cache: bool, trials: int) -> None:
    reg = _autotune_metrics()
    reg.gauge("hvd_autotune_locked",
              help="1 once the mesh autotuner locked a plan").set(1.0)
    reg.gauge("hvd_autotune_plan_bucket_bytes",
              help="bucket byte budget of the locked plan"
              ).set(float(plan.bucket_bytes))
    reg.gauge("hvd_autotune_plan_small_floor_bytes",
              help="small-bucket latency floor of the locked plan"
              ).set(float(plan.small_floor))
    # exactly ONE combination may read 1: a re-lock (elastic re-mesh
    # retune) must zero the previously active series, or the fleet view
    # shows two live plans at once
    for algo in _ALGORITHMS:
        for codec in _CODECS:
            reg.gauge("hvd_autotune_plan",
                      help="locked plan identity (1 on the active "
                           "algorithm/codec combination)",
                      labels={"algorithm": algo, "codec": codec}).set(
                1.0 if (algo, codec) == (plan.algorithm, plan.codec)
                else 0.0)
    if best_s is not None:
        reg.gauge("hvd_autotune_best_step_seconds",
                  help="measured step seconds of the locked plan"
                  ).set(best_s)
    if hasattr(plan, "schedule"):
        # a locked ParallelPlan also lands the pipeline-layout gauges
        # (hvd_pipeline_*, docs/OBSERVABILITY.md "Pipeline metrics")
        from horovod_tpu.train.pipeline import _pipeline_metrics
        _pipeline_metrics(plan)
    if from_cache:
        reg.counter("hvd_autotune_cache_hits_total",
                    help="runs that started from a cached tuned plan "
                         "with zero search trials").inc()
    from horovod_tpu.diagnostics.flight_recorder import record_event
    record_event("autotune_locked", plan=plan.key,
                 from_cache=from_cache, trials=trials,
                 best_step_s=best_s)


class AutotuneController:
    """Budget-bounded successive halving over candidate :class:`Plan`\\ s.

    Drive it one step at a time: ``begin_step()`` names the plan to run,
    ``end_step(seconds)`` (or :meth:`observe` from an external clock
    like ``StepTimer``) scores it. The first step a plan runs in a
    round is a WARMUP — it absorbs the plan's compile — and is never
    scored. When one survivor remains, or ``budget_steps`` search steps
    have been consumed, the best plan locks: ``locked_plan`` is set,
    metrics/flight/CSV record the choice, and the cache (when
    configured) is written so the next run starts locked with zero
    trials.
    """

    def __init__(self, plans: Sequence[Plan], *,
                 budget_steps: Optional[int] = None,
                 steps_per_trial: int = 2,
                 log_path: Optional[str] = None,
                 cache: Optional[PlanCache] = None,
                 fingerprint: Optional[str] = None) -> None:
        if not plans:
            raise ValueError("need at least one candidate plan")
        if budget_steps is None:
            from horovod_tpu.common.config import get_config
            budget_steps = get_config().autotune_budget_steps
        self.budget_steps = max(1, int(budget_steps))
        self.steps_per_trial = max(1, int(steps_per_trial))
        self.cache = cache
        self.fingerprint = fingerprint
        self._log_path = log_path
        self._log_header_written = False
        # trim the speculative tail so at least one full scoring round
        # fits the budget — and SAY what was dropped (no silent caps)
        per_plan = 1 + self.steps_per_trial
        max_plans = max(1, self.budget_steps // per_plan)
        plans = list(dict.fromkeys(plans))
        if len(plans) > max_plans:
            dropped = plans[max_plans:]
            log.warning(
                "autotune budget %d steps fits %d of %d candidate "
                "plans (%d steps each); dropping: %s",
                self.budget_steps, max_plans, len(plans), per_plan,
                ", ".join(p.key for p in dropped))
            plans = plans[:max_plans]
        self._survivors: List[Plan] = plans
        self._round = 0
        self._trial_steps = self.steps_per_trial
        self._scores: Dict[Plan, float] = {}
        self._samples: List[float] = []
        self._plan_idx = 0
        self._step_in_plan = 0
        self.steps_used = 0
        self.trials = 0          # scored (non-warmup) measurements
        self.from_cache = False
        self.locked_plan: Optional[Plan] = None
        self.best_seconds: Optional[float] = None
        self._pending: Optional[Plan] = None

    # -- cache warm start ---------------------------------------------------

    def try_cache(self) -> bool:
        """Adopt a cached plan for this controller's fingerprint; True
        when warm (zero trials will run)."""
        if self.cache is None or not self.fingerprint:
            return False
        plan = self.cache.load(self.fingerprint)
        if plan is None:
            return False
        self.from_cache = True
        self._lock(plan, best=None)
        log.info("autotune: warm plan cache hit — locked %s with zero "
                 "search trials", plan.key)
        return True

    # -- stepping -----------------------------------------------------------

    @property
    def done(self) -> bool:
        return self.locked_plan is not None

    def begin_step(self) -> Plan:
        """The plan the NEXT training step should run."""
        if self.locked_plan is not None:
            return self.locked_plan
        self._pending = self._survivors[self._plan_idx]
        return self._pending

    def end_step(self, seconds: float) -> None:
        """Score the step issued by the last ``begin_step``."""
        if self.locked_plan is not None:
            return
        plan = self._pending
        if plan is None:
            return
        self._pending = None
        self.steps_used += 1
        warmup = self._step_in_plan == 0
        self._step_in_plan += 1
        if not warmup:
            self._samples.append(float(seconds))
            self.trials += 1
            reg = _autotune_metrics()
            reg.counter("hvd_autotune_trials_total",
                        help="scored mesh-autotune trial steps").inc()
            reg.gauge("hvd_autotune_trial_step_seconds",
                      help="last scored trial step time",
                      labels={"plan": plan.key}).set(float(seconds))
            from horovod_tpu.diagnostics.flight_recorder import record_event
            record_event("autotune_trial", plan=plan.key,
                         round=self._round, step_s=round(seconds, 6))
        if self._step_in_plan >= 1 + self._trial_steps:
            # plan's window complete. Score = MIN over the window:
            # contention only ever adds time, so the fastest observed
            # step is the cleanest estimate of what the plan can do —
            # the same best-of estimator bench.py and the overlap bench
            # use (a mean/median would let one scheduler hiccup on a
            # loaded box evict the true winner)
            if self._samples:
                score = min(self._samples)
                self._scores[plan] = score
                self._log_trial(plan, score)
            self._samples = []
            self._step_in_plan = 0
            self._plan_idx += 1
            if self._plan_idx >= len(self._survivors):
                self._finish_round()
        if self.locked_plan is None and self.steps_used >= self.budget_steps:
            self._lock_best("step budget exhausted")

    # external clock (StepTimer / the PR-7 time-series ring feed)
    observe = end_step

    def _finish_round(self) -> None:
        scored = [p for p in self._survivors if p in self._scores]
        if not scored:
            self._lock_best("no scored plans")
            return
        scored.sort(key=lambda p: self._scores[p])
        keep = max(1, len(scored) // 2)
        if keep == 1 or self.steps_used >= self.budget_steps:
            # a lone survivor cannot be out-raced by anyone: locking now
            # saves an entire doubled re-measurement window of pure
            # timing overhead
            self._lock(scored[0], best=self._scores[scored[0]])
            return
        self._survivors = scored[:keep]
        self._round += 1
        self._trial_steps *= 2  # fewer survivors, finer measurement
        self._plan_idx = 0
        self._step_in_plan = 0
        log.info("autotune round %d: %d survivors (best %s @ %.6fs)",
                 self._round, len(self._survivors), scored[0].key,
                 self._scores[scored[0]])

    def _lock_best(self, why: str) -> None:
        if self._scores:
            best = min(self._scores, key=self._scores.get)
            self._lock(best, best=self._scores[best])
        else:
            # budget too small to score anything: the baseline
            # (first candidate) is the only defensible choice
            self._lock(self._survivors[0], best=None)
        log.info("autotune: locked %s (%s, %d scored trials, %d steps)",
                 self.locked_plan.key, why, self.trials, self.steps_used)

    def _lock(self, plan: Plan, best: Optional[float]) -> None:
        self.locked_plan = plan
        self.best_seconds = best
        _record_locked_plan(plan, best, self.from_cache, self.trials)
        self._log_trial(plan, best if best is not None else float("nan"),
                        final=True)
        if self.cache is not None and self.fingerprint \
                and not self.from_cache:
            self.cache.store(self.fingerprint, plan, meta={
                "best_step_seconds": best,
                "trials": self.trials,
                "steps_used": self.steps_used,
            })

    # -- CSV trace (like the C++ core's HVD_TPU_AUTOTUNE_LOG) ---------------

    _CSV_HEADER = ("round,bucket_bytes,algorithm,codec,small_floor,"
                   "plan,step_s,final\n")

    def _log_trial(self, plan: Plan, score: float,
                   final: bool = False) -> None:
        if not self._log_path:
            return
        try:
            # append-only: a second controller in the same process (an
            # elastic re-mesh retuning) must extend the audit trail, not
            # truncate the previous search's rows. Header only when the
            # file is new/empty. A trace written under an OLDER column
            # schema is rotated to <path>.v1 first — appending 8-field
            # rows under a 7-column header would silently misalign every
            # consumer parsing by header.
            if not self._log_header_written \
                    and os.path.exists(self._log_path):
                with open(self._log_path) as f:
                    first = f.readline()
                if first and first != self._CSV_HEADER:
                    os.replace(self._log_path, self._log_path + ".v1")
                    log.info("autotune CSV trace %s used an older "
                             "schema; rotated to %s.v1",
                             self._log_path, self._log_path)
            with open(self._log_path, "a") as f:
                if not self._log_header_written:
                    if f.tell() == 0:
                        f.write(self._CSV_HEADER)
                    self._log_header_written = True
                f.write(f"{self._round},{plan.bucket_bytes},"
                        f"{plan.algorithm},{plan.codec},"
                        f"{plan.small_floor},{plan.key},{score:.6f},"
                        f"{1 if final else 0}\n")
        except OSError:
            pass  # the trace is advisory, never fatal


# ---------------------------------------------------------------------------
# The autotune= seam behind make_overlap_train_step
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class AutotuneOptions:
    """Configuration for the ``autotune=`` seam. ``True`` resolves to
    env-driven defaults (``HVD_TPU_AUTOTUNE_BUDGET_STEPS``,
    ``HVD_TPU_AUTOTUNE_CACHE_DIR``, ``HVD_TPU_AUTOTUNE_LOG``)."""

    budget_steps: Optional[int] = None
    steps_per_trial: int = 2
    cache_dir: Optional[str] = None
    log_path: Optional[str] = None
    plans: Optional[Sequence[Plan]] = None
    include_fp8: bool = False

    @classmethod
    def resolve(cls, autotune) -> "AutotuneOptions":
        if isinstance(autotune, AutotuneOptions):
            return autotune
        if autotune is True or autotune is None:
            return cls()
        if isinstance(autotune, Plan):
            # a pinned plan: zero search, just realize it
            return cls(plans=[autotune], budget_steps=1)
        raise TypeError(
            f"autotune= takes True, AutotuneOptions or Plan; got "
            f"{autotune!r}")

    def resolved_log_path(self) -> str:
        if self.log_path is not None:
            return self.log_path
        from horovod_tpu.common.config import get_config
        base = get_config().autotune_log
        return (base + ".mesh.csv") if base else ""


class AutotunedStep:
    """Callable train step that searches, then serves.

    While searching, every call picks the controller's candidate plan,
    runs that plan's compiled step, blocks for the result and feeds the
    measured wall time back. Once locked (search converged, budget
    spent, or warm cache hit on the first call), calls dispatch straight
    to the winning compiled step with zero added overhead.
    """

    def __init__(self, build_step: Callable[[Plan], Callable],
                 controller_factory: Callable[[Any], AutotuneController]
                 ) -> None:
        self._build_step = build_step
        self._controller_factory = controller_factory
        self._steps: Dict[Plan, Callable] = {}
        self.autotune: Optional[AutotuneController] = None
        self._locked_fn: Optional[Callable] = None

    def _get(self, plan: Plan) -> Callable:
        fn = self._steps.get(plan)
        if fn is None:
            fn = self._steps[plan] = self._build_step(plan)
        return fn

    def __call__(self, params, opt_state, batch):
        import jax
        if self.autotune is None:
            # first call: the params tree is finally in hand — resolve
            # the fingerprint and try the warm cache before any trial
            self.autotune = self._controller_factory(params)
        ctl = self.autotune
        if self._locked_fn is None and ctl.locked_plan is not None:
            self._locked_fn = self._get(ctl.locked_plan)
        if self._locked_fn is not None:
            return self._locked_fn(params, opt_state, batch)
        plan = ctl.begin_step()
        fn = self._get(plan)
        t0 = time.perf_counter()
        out = fn(params, opt_state, batch)
        jax.block_until_ready(out)
        ctl.end_step(time.perf_counter() - t0)
        if ctl.locked_plan is not None:
            self._locked_fn = self._get(ctl.locked_plan)
        return out


def make_autotuned_train_step(loss_fn, optimizer, mesh,
                              axis_name: str = "dp", *,
                              autotune=True,
                              n_micro: int = 1,
                              op=None,
                              bucket_bytes: Optional[int] = None,
                              compression=None,
                              ring: bool = False,
                              algorithm: Optional[str] = None,
                              topology=None,
                              small_floor: Optional[int] = None,
                              overlap: bool = True,
                              sync: bool = True,
                              donate: bool = True,
                              guard=None) -> AutotunedStep:
    """Build the searching/serving step for
    ``make_overlap_train_step(..., autotune=...)``.

    The explicit communication kwargs (``bucket_bytes`` / ``algorithm``
    / ``compression`` / ``small_floor``) become the BASELINE candidate —
    the search can only confirm or beat the hand-set config, and the
    tuned-vs-default CI gate (``ci/check_bench.py --tuned``) holds it to
    that.
    """
    from horovod_tpu.common.topology import detect_topology
    from horovod_tpu.ops.reduce_op import Average
    from horovod_tpu.train.buckets import resolve_bucket_bytes
    from horovod_tpu.train.overlap import (make_overlap_train_step,
                                           resolve_small_floor)

    opts = AutotuneOptions.resolve(autotune)
    if op is None:
        op = Average
    topo = topology if topology is not None \
        else detect_topology(mesh, axis_name)
    world = int(mesh.shape[axis_name])
    baseline = Plan(
        bucket_bytes=resolve_bucket_bytes(bucket_bytes),
        algorithm=algorithm or ("ring" if ring else "psum"),
        codec=_codec_name(compression),
        small_floor=resolve_small_floor(small_floor))
    plans = list(opts.plans) if opts.plans else candidate_plans(
        topo, baseline=baseline, include_fp8=opts.include_fp8)
    cache_dir = resolve_cache_dir(opts.cache_dir)
    cache = PlanCache(cache_dir) if cache_dir else None
    # comm plans are tuned UNDER a fixed mesh: the key carries that
    # mesh's pp size (the eager DistributedOptimizer seam has no mesh
    # and reconstructs the key with the default pp=1)
    mesh_shape = topology_key(topo, pp=int(mesh.shape.get("pp", 1)))

    def build_step(plan: Plan):
        # autotune=False is load-bearing: with HVD_TPU_AUTOTUNE_MESH=1
        # the factory's env default would otherwise re-enter THIS
        # function for every candidate, forever
        return make_overlap_train_step(
            loss_fn, optimizer, mesh, axis_name, n_micro=n_micro, op=op,
            overlap=overlap, sync=sync, donate=donate, autotune=False,
            guard=guard, **plan.step_kwargs(topo))

    def controller_factory(params) -> AutotuneController:
        fp = plan_fingerprint(params, mesh_shape, world)
        ctl = AutotuneController(
            plans, budget_steps=opts.budget_steps,
            steps_per_trial=opts.steps_per_trial,
            log_path=opts.resolved_log_path(),
            cache=cache, fingerprint=fp)
        ctl.try_cache()
        return ctl

    return AutotunedStep(build_step, controller_factory)


# ---------------------------------------------------------------------------
# ISSUE 11: the PARALLELISM plan joins the same search
# ---------------------------------------------------------------------------

def parallel_candidate_plans(world: int, n_layers: int, *,
                             baseline=None,
                             schedules: Sequence[str] = ("1f1b", "gpipe",
                                                         "interleaved"),
                             max_pp: Optional[int] = None,
                             include_comms: bool = True) -> List[Any]:
    """The discrete (dp x pp) x schedule x n_microbatches x comms search
    space for :func:`make_parallel_train_step`, most-promising-first.

    Layout candidates: every pp that divides both the world and the
    layer count (pp=1 — pure DP with the comm defaults — is the
    baseline and always first: the search can only confirm or beat it).
    Per pipeline layout: each schedule, microbatch counts {pp, 2*pp}
    (enough to fill the pipe vs halve the bubble), and interleaved adds
    ``virtual_stages=2`` where the layers split. ``include_comms`` adds
    an int8-codec bucketed-sync variant of each layout with dp > 1 —
    (pp, M, schedule) joining bucket x algorithm x codec as axes of ONE
    search, per the ROADMAP. The tail is ordered cheapest-compile-first
    so budget trimming (the controller's no-silent-caps warning) drops
    the speculative end."""
    from horovod_tpu.parallel.plan import ParallelPlan
    from horovod_tpu.train.buckets import resolve_bucket_bytes

    plans: List[Any] = []
    if baseline is not None:
        plans.append(baseline)
    plans.append(ParallelPlan(dp=world, pp=1))
    pps = [p for p in range(2, (max_pp or world) + 1)
           if world % p == 0 and n_layers % p == 0]
    comm_variant = Plan(resolve_bucket_bytes(None), "psum", "int8") \
        if include_comms else None
    for pp in pps:
        dp = world // pp
        for M in (pp, 2 * pp):
            for schedule in schedules:
                if schedule == "interleaved":
                    if n_layers % (pp * 2) != 0:
                        continue
                    v = 2
                else:
                    v = 1
                plans.append(ParallelPlan(
                    dp=dp, pp=pp, schedule=schedule, n_microbatches=M,
                    virtual_stages=v))
                if comm_variant is not None and dp > 1:
                    plans.append(ParallelPlan(
                        dp=dp, pp=pp, schedule=schedule,
                        n_microbatches=M, virtual_stages=v,
                        comms=comm_variant))
    seen, out = set(), []
    for p in plans:
        if p not in seen:
            seen.add(p)
            out.append(p)
    return out


class ParallelAutotunedStep:
    """Searching/serving step over whole :class:`ParallelPlan`\\ s.

    Like :class:`AutotunedStep`, but candidate filtering needs the BATCH
    (a plan whose ``dp * n_microbatches`` does not tile the global batch
    cannot compile), so the controller is constructed on the first call
    when params AND batch are finally in hand. Candidate steps keep the
    caller's params in natural layer order — each candidate permutes
    in/out of its own storage layout internally — so one (params,
    opt_state) pair flows through every trial unchanged. Once locked,
    ``pin()`` returns the underlying
    :class:`~horovod_tpu.train.pipeline.PipelineTrainStep` for
    permutation-free steady state (pin once, re-``prepare_params``)."""

    def __init__(self, plans: Sequence[Any],
                 build_step: Callable[[Any], Any],
                 controller_factory: Callable, n_layers: int) -> None:
        self._plans = list(plans)
        self._build_step = build_step
        self._controller_factory = controller_factory
        self._n_layers = n_layers
        self._steps: Dict[Any, Callable] = {}
        self._raw: Dict[Any, Any] = {}
        self.autotune: Optional[AutotuneController] = None
        self._locked_fn: Optional[Callable] = None

    def _fits(self, plan, batch_dim: int, n_layers: int) -> bool:
        per_replica = batch_dim // plan.dp if batch_dim % plan.dp == 0 \
            else 0
        return (batch_dim % plan.dp == 0
                and per_replica % plan.n_microbatches == 0
                and n_layers % plan.total_stages == 0)

    def _get(self, plan):
        fn = self._steps.get(plan)
        if fn is None:
            raw = self._build_step(plan)
            self._raw[plan] = raw

            def fn(params, opt_state, batch, _raw=raw):
                p = _raw.prepare_params(params)
                o = _raw.prepare_params(opt_state)
                p, o, loss = _raw(p, o, batch)
                return (_raw.restore_params(p), _raw.restore_params(o),
                        loss)
            self._steps[plan] = fn
        return fn

    def pin(self):
        """The locked plan's bare step (natural-order permutation
        stripped); None while still searching."""
        ctl = self.autotune
        if ctl is None or ctl.locked_plan is None:
            return None
        self._get(ctl.locked_plan)
        return self._raw[ctl.locked_plan]

    def __call__(self, params, opt_state, batch):
        import jax
        if self.autotune is None:
            leaves = jax.tree_util.tree_leaves(batch)
            batch_dim = int(leaves[0].shape[0])
            self.autotune = self._controller_factory(
                params, batch_dim,
                lambda plan: self._fits(plan, batch_dim,
                                        self._n_layers))
        ctl = self.autotune
        if self._locked_fn is None and ctl.locked_plan is not None:
            self._locked_fn = self._get(ctl.locked_plan)
        if self._locked_fn is not None:
            return self._locked_fn(params, opt_state, batch)
        plan = ctl.begin_step()
        fn = self._get(plan)
        t0 = time.perf_counter()
        out = fn(params, opt_state, batch)
        jax.block_until_ready(out)
        ctl.end_step(time.perf_counter() - t0)
        if ctl.locked_plan is not None:
            self._locked_fn = self._get(ctl.locked_plan)
        return out


def make_parallel_train_step(layer_fn, loss_fn, optimizer, *,
                             n_layers: int,
                             devices=None,
                             autotune=True,
                             op=None,
                             donate: bool = True,
                             guard=None
                             ) -> ParallelAutotunedStep:
    """Search the unified parallelism space (ROADMAP 1, ISSUE 11): the
    dp x pp split, pipeline schedule, microbatch count and dp
    communication plan are scored together by measured step time on the
    layer-major model, successive-halving style, and the winner is
    fingerprinted into the SAME persistent plan cache as the
    communication tuner — a warm hit on a re-meshed world locks the
    full parallelism plan with zero trials.

    Called by ``make_pipeline_train_step(..., autotune=...)``; the model
    contract is that factory's layer-major one. The pure-DP layout
    (dp=world, pp=1) is always the baseline candidate."""
    import jax

    from horovod_tpu.common.topology import detect_topology, flat_topology
    from horovod_tpu.ops.reduce_op import Average

    if op is None:
        op = Average
    opts = AutotuneOptions.resolve(autotune)
    devs = list(devices) if devices is not None else list(jax.devices())
    world = len(devs)
    try:
        topo = detect_topology(n=world)
    except Exception:
        topo = flat_topology(world)
    plans = list(opts.plans) if opts.plans else parallel_candidate_plans(
        world, n_layers)
    cache_dir = resolve_cache_dir(opts.cache_dir)
    cache = PlanCache(cache_dir) if cache_dir else None
    # pp=0: the dp x pp split is itself a searched axis (see
    # topology_key); the key identifies the WORLD + model
    mesh_shape = topology_key(topo, pp=0)

    def build_step(plan):
        from horovod_tpu.train.pipeline import make_pipeline_train_step
        return make_pipeline_train_step(
            layer_fn, loss_fn, optimizer, plan=plan, n_layers=n_layers,
            devices=devs, op=op, donate=donate, autotune=False,
            guard=guard)

    def controller_factory(params, batch_dim: int,
                           fits) -> AutotuneController:
        usable = [p for p in plans if fits(p)]
        dropped = [p for p in plans if not fits(p)]
        if dropped:
            log.info(
                "parallel autotune: %d of %d candidate plans cannot "
                "tile batch=%d x %d layers and were skipped: %s",
                len(dropped), len(plans), batch_dim, n_layers,
                ", ".join(p.key for p in dropped[:8])
                + ("..." if len(dropped) > 8 else ""))
        if not usable:
            raise ValueError(
                f"no parallelism plan tiles global batch {batch_dim} "
                f"over {world} devices with {n_layers} layers")
        fp = plan_fingerprint(params, mesh_shape, world)
        ctl = AutotuneController(
            usable, budget_steps=opts.budget_steps,
            steps_per_trial=opts.steps_per_trial,
            log_path=opts.resolved_log_path(),
            cache=cache, fingerprint=fp)
        # the fingerprint covers tree+world, NOT the batch: a cached
        # plan tuned at another global batch size may not tile this
        # one. Validate BEFORE adopting — the documented cache contract
        # is "stale entries retune, never crash"
        cached = cache.load(fp) if cache is not None else None
        if cached is not None and (not hasattr(cached, "total_stages")
                                   or not fits(cached)):
            log.warning(
                "cached parallelism plan %s cannot tile global batch "
                "%d x %d layers on this run; retuning",
                getattr(cached, "key", cached), batch_dim, n_layers)
        else:
            ctl.try_cache()
        return ctl

    return ParallelAutotunedStep(plans, build_step, controller_factory,
                                 n_layers)
