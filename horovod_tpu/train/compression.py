"""Back-compat shim: the compression subsystem moved to
:mod:`horovod_tpu.compression` (quantizers, error feedback, Pallas
kernels, wire paths — see docs/PERF.md "Gradient compression").

This module keeps the original import surface
(``horovod_tpu.train.compression.Compression`` et al., mirroring the
reference's ``horovod/torch/compression.py``) alive for existing
callers; new code should import from ``horovod_tpu.compression``.
"""

from __future__ import annotations

from horovod_tpu.compression import (  # noqa: F401
    BF16Compressor,
    Compression,
    Compressor,
    ErrorFeedback,
    FP16Compressor,
    NoneCompressor,
)
from horovod_tpu.compression.base import _astype  # noqa: F401
