"""Gradient compression for transport (reference:
``horovod/torch/compression.py:20-75`` — ``Compression.none`` / ``fp16``
compress/decompress pairs around allreduce).

On TPU, bf16 is the native 16-bit format (MXU-friendly, same exponent range
as fp32), so ``Compression.bf16`` is the recommended choice; ``fp16`` is kept
for parity with the reference.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np


def _astype(tensor, dtype):
    if isinstance(tensor, np.ndarray):
        return tensor.astype(dtype)
    return tensor.astype(dtype)


class Compressor:
    """Interface (reference: ``Compressor`` base, ``compression.py:20-33``)."""

    @staticmethod
    def compress(tensor) -> Tuple:
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Compress float32/float64 to float16 for transport
    (reference: ``compression.py:42-62``)."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.float16:
            return _astype(tensor, jnp.float16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else _astype(tensor, ctx)


class BF16Compressor(Compressor):
    """TPU-native 16-bit compression (no reference analog; bf16 keeps fp32's
    exponent range so gradient overflow handling is unnecessary)."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.bfloat16:
            return _astype(tensor, jnp.bfloat16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else _astype(tensor, ctx)


class Compression:
    """Namespace matching the reference's public API
    (``hvd.Compression.none`` / ``.fp16``; ``compression.py:65-75``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
