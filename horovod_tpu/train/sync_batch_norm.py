"""Synchronized batch normalization across data-parallel workers.

Reference: ``horovod/torch/sync_batch_norm.py:40-218`` (custom autograd
Function allgathering per-rank moments) and
``horovod/tensorflow/sync_batch_norm.py``. TPU-native: inside SPMD the
cross-replica moments are one ``lax.pmean`` over the data axes — XLA fuses
it into the surrounding elementwise work, no custom gradient needed (psum
differentiates correctly).

Two forms:
* :func:`sync_batch_norm_spmd` — functional, for shard_map/manual-SPMD code.
* :class:`SyncBatchNorm` — flax module, drop-in for ``nn.BatchNorm`` in
  GSPMD-auto models (e.g. the ResNet family).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import flax.linen as nn

from horovod_tpu._compat import axis_size


def _axes_live(axis_names: Sequence[str]) -> Tuple[str, ...]:
    out = []
    for name in axis_names:
        try:
            if axis_size(name) > 1:
                out.append(name)
        except NameError:
            pass
    return tuple(out)


def sync_batch_norm_spmd(x: jax.Array, scale: jax.Array, bias: jax.Array,
                         axis_names: Sequence[str] = ("dp",),
                         eps: float = 1e-5) -> jax.Array:
    """Normalize ``x [..., C]`` with moments reduced over the batch dims AND
    the given mesh axes (the sync part)."""
    red = tuple(range(x.ndim - 1))
    mean = jnp.mean(x.astype(jnp.float32), axis=red)
    mean_sq = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=red)
    live = _axes_live(axis_names)
    if live:
        mean = lax.pmean(mean, live)
        mean_sq = lax.pmean(mean_sq, live)
    var = mean_sq - jnp.square(mean)
    y = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


class SyncBatchNorm(nn.Module):
    """flax BatchNorm with cross-worker statistics.

    In GSPMD-auto mode (jit over a mesh with batch sharded), plain
    ``jnp.mean`` over the batch dim is ALREADY global — XLA inserts the
    collective from shardings — so this module's value is (a) parity of
    surface with the reference API and (b) correctness under
    shard_map/manual collectives where ``axis_names`` must be explicit.
    """

    use_running_average: Optional[bool] = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None
    axis_names: Optional[Sequence[str]] = None

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param("use_running_average",
                                self.use_running_average,
                                use_running_average) \
            if (self.use_running_average is not None
                or use_running_average is not None) else False
        C = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(C, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(C, jnp.float32))
        scale = self.param("scale", nn.initializers.ones, (C,))
        bias = self.param("bias", nn.initializers.zeros, (C,))

        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            red = tuple(range(x.ndim - 1))
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=red)
            mean_sq = jnp.mean(jnp.square(xf), axis=red)
            live = _axes_live(self.axis_names or ())
            if live:
                mean = lax.pmean(mean, live)
                mean_sq = lax.pmean(mean_sq, live)
            var = mean_sq - jnp.square(mean)
            if not self.is_initializing():
                # Running var gets the unbiased (n/(n-1)) estimate over the
                # GLOBAL batch, matching reference torch SyncBatchNorm
                # (sync_batch_norm.py:~190); the biased var still normalizes.
                n = int(np.prod([x.shape[d] for d in red]))
                for a in live:
                    n *= axis_size(a)
                corr = n / (n - 1) if n > 1 else 1.0
                ra_mean.value = (self.momentum * ra_mean.value
                                 + (1 - self.momentum) * mean)
                ra_var.value = (self.momentum * ra_var.value
                                + (1 - self.momentum) * var * corr)

        y = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + self.epsilon)
        y = y * scale + bias
        return y.astype(self.dtype or x.dtype)
