"""Back-compat shim: checkpointing moved to :mod:`horovod_tpu.checkpoint`.

``Checkpointer`` is now the NATIVE sharded store
(:class:`horovod_tpu.checkpoint.ShardedCheckpointer`) — dependency-free,
async two-phase commit, elastic resharding restore (docs/ELASTIC.md
"Durable commits").  It keeps the old wrapper's surface
(``save``/``latest_step``/``restore``/``restore_latest``/``close`` and
the ``like=`` re-meshing contract), so existing callers keep working
with no orbax installed.

The orbax path survives as :class:`OrbaxCheckpointer` for users who
need orbax's format (e.g. to interoperate with flax/orbax tooling); its
import is optional — precedent: ``train/compression.py`` shimming the
compression subsystem.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from horovod_tpu.checkpoint import CheckpointError  # noqa: F401
from horovod_tpu.checkpoint import ShardedCheckpointer

# The native store is the default checkpointer.
Checkpointer = ShardedCheckpointer


class OrbaxCheckpointer:
    """Thin orbax wrapper for (step → pytree) training state.

    Optional: needs the ``orbax-checkpoint`` package.  The default
    ``Checkpointer`` (:class:`horovod_tpu.checkpoint.ShardedCheckpointer`)
    covers sharded save / cross-mesh restore without it.
    """

    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        try:
            import orbax.checkpoint as ocp
        except ImportError as e:
            raise ImportError(
                "orbax-checkpoint is not installed. The native sharded "
                "store is the default and needs no extra dependency — "
                "use horovod_tpu.Checkpointer "
                "(horovod_tpu.checkpoint.ShardedCheckpointer); "
                "OrbaxCheckpointer exists only for orbax-format "
                "interoperability.") from e
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        import orbax.checkpoint as ocp
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: int, like: Any = None) -> Any:
        """Restore ``step``; ``like`` (a pytree of arrays or ShapeDtypeStruct
        with shardings) places shards onto the current mesh."""
        import jax
        import orbax.checkpoint as ocp
        if like is not None:
            def abstractify(x):
                if isinstance(x, jax.ShapeDtypeStruct):
                    return x
                if hasattr(x, "shape") and hasattr(x, "dtype"):
                    return jax.ShapeDtypeStruct(
                        x.shape, x.dtype,
                        sharding=getattr(x, "sharding", None))
                return x  # scalars / python leaves restore as stored
            abstract = jax.tree_util.tree_map(abstractify, like)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        return self._mgr.restore(step)

    def restore_latest(self, like: Any = None) -> Optional[Any]:
        step = self.latest_step()
        if step is None:
            return None
        return self.restore(step, like)

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
