"""Checkpoint/resume for JAX training state.

The reference has NO core checkpoint subsystem (SURVEY.md §5: elastic
``State`` objects commit to host memory; Spark estimators write framework
files through the Store). Here checkpointing is first-class and TPU-native:
orbax writes sharded arrays directly from device memory (each host saves
its shards — no gather), and restore places shards onto the current mesh,
which is exactly what elastic re-meshing needs.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


class Checkpointer:
    """Thin orbax wrapper for (step → pytree) training state.

    Usage::

        ckpt = Checkpointer("/path/run1")
        ckpt.save(step, {"params": params, "opt_state": opt_state})
        state = ckpt.restore_latest(like={"params": params_shape, ...})
    """

    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        import orbax.checkpoint as ocp
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: int, like: Any = None) -> Any:
        """Restore ``step``; ``like`` (a pytree of arrays or ShapeDtypeStruct
        with shardings) places shards onto the current mesh."""
        import orbax.checkpoint as ocp
        if like is not None:
            def abstractify(x):
                if isinstance(x, jax.ShapeDtypeStruct):
                    return x
                if hasattr(x, "shape") and hasattr(x, "dtype"):
                    return jax.ShapeDtypeStruct(
                        x.shape, x.dtype,
                        sharding=getattr(x, "sharding", None))
                return x  # scalars / python leaves restore as stored
            abstract = jax.tree_util.tree_map(abstractify, like)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        return self._mgr.restore(step)

    def restore_latest(self, like: Any = None) -> Optional[Any]:
        step = self.latest_step()
        if step is None:
            return None
        return self.restore(step, like)

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
