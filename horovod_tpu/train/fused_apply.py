"""Fused dequantize + optimizer apply: the quantized-gradient tail in
one kernel pass.

With quantized gradient sync (PR 2) the step's tail used to serialize
three full-tensor HBM sweeps after the collective: dequantize the int8
codes, update the momentum/Adam moments, form the delta. The Pallas
kernels in :mod:`horovod_tpu.ops.pallas_quantize` (``fused_sgd_apply``,
``fused_adam_apply``) collapse that into one VMEM round trip, and
``block_quantize_ef`` produces the error-feedback residual in the same
pass that makes the codes — so the whole compress→carry→apply chain
reads each gradient byte once.

Use via :func:`horovod_tpu.DistributedOptimizer`::

    tx = hvd.DistributedOptimizer(hvd.fused_sgd(0.1, momentum=0.9),
                                  compression=hvd.ErrorFeedback(
                                      hvd.Compression.int8))

``fused_sgd``/``fused_adam`` return a :class:`FusedOptSpec` — a
descriptor, not an optax transform — which ``DistributedOptimizer``
lowers into a single gradient transformation that fuses sync and apply.
Regimes (same routing logic as ``DistributedGradTransform``):

* **global-SPMD jit / single process** — the flagship bench regime: the
  sync is an identity (XLA reduces from shardings), so the kernel
  consumes the local codes directly: fully fused.
* **shard_map with a live axis** — codes are dequantized into the
  in-graph ``preduce`` (quantized payloads aren't sum-reducible), then
  the same update math runs on the reduced blocks (XLA path).
* **eager multi-process** — the qdq'd gradients ride the existing
  quantized wire (block-int8 requantization is exact — ``quantize ∘
  dequantize ∘ quantize = quantize`` — so re-entering the wire path
  costs one redundant codec pass, not accuracy), then blocked apply.

Hyperparameters are scalars (traced values are fine — they ride in
SMEM); optax-style schedules are not supported here. All optimizer
state (moments, EF residual) lives in the codec's blocked ``[n_blocks,
block]`` fp32 layout so it feeds the kernels without reshuffling.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.common.basics import size
from horovod_tpu.common.process_sets import ProcessSet, global_process_set
from horovod_tpu.common.util import is_traced as _is_traced
from horovod_tpu.compression.error_feedback import ErrorFeedback
from horovod_tpu.compression.quantizers import BlockInt8Quantizer
from horovod_tpu.ops.reduce_op import Average, ReduceOp, Sum

_tree = jax.tree_util


class FusedOptSpec(NamedTuple):
    """Descriptor for a fusable optimizer; build with :func:`fused_sgd`
    or :func:`fused_adam` and hand to ``DistributedOptimizer``."""

    kind: str  # "sgd" | "adam"
    lr: Any
    momentum: Any = 0.0
    b1: Any = 0.9
    b2: Any = 0.999
    eps: Any = 1e-8

    def to_optax(self) -> optax.GradientTransformation:
        """Reference (unfused) optax equivalent — parity tests and
        fallbacks."""
        if self.kind == "sgd":
            return optax.sgd(self.lr,
                             momentum=self.momentum or None)
        return optax.adam(self.lr, b1=self.b1, b2=self.b2, eps=self.eps)


def fused_sgd(learning_rate, momentum=0.0) -> FusedOptSpec:
    """SGD(+momentum) with the fused dequantize+apply kernel
    (optax.sgd numerics)."""
    return FusedOptSpec("sgd", learning_rate, momentum=momentum)


def fused_adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8) -> FusedOptSpec:
    """Adam with the fused dequantize+apply kernel (optax.adam
    numerics, bias correction included)."""
    return FusedOptSpec("adam", learning_rate, b1=b1, b2=b2, eps=eps)


class FusedOptState(NamedTuple):
    count: jax.Array
    mom: Any        # sgd momentum / adam first moment (blocked fp32)
    vel: Any        # adam second moment (blocked fp32) or None leaves
    residual: Any   # EF residual (blocked fp32) or None leaves


def _leaf_meta(leaf, block: int) -> Tuple[int, int]:
    n = int(np.prod(leaf.shape, dtype=np.int64)) if leaf.shape else 1
    return n, -(-n // block)  # (elements, n_blocks)


def _to_blocks(x, block: int) -> jax.Array:
    flat = jnp.ravel(x).astype(jnp.float32)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    return flat.reshape(-1, block)


def _from_blocks(blocks, leaf) -> jax.Array:
    n = int(np.prod(leaf.shape, dtype=np.int64)) if leaf.shape else 1
    return blocks.reshape(-1)[:n].reshape(leaf.shape).astype(leaf.dtype)


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def make_fused_transform(spec: FusedOptSpec,
                         op: ReduceOp = Average,
                         process_set: ProcessSet = global_process_set,
                         compression=None,
                         axis_name: Optional[str] = None
                         ) -> optax.GradientTransformation:
    """Lower a :class:`FusedOptSpec` + int8 codec into one optax
    transform fusing EF-quantized gradient sync with the optimizer
    apply (see module docstring for regime routing). Called by
    ``DistributedOptimizer``; usable directly in an optax chain."""
    if spec.kind not in ("sgd", "adam"):
        raise ValueError(f"unknown fused optimizer kind {spec.kind!r}")
    ef = isinstance(compression, ErrorFeedback)
    codec = compression.inner if ef else compression
    if not isinstance(codec, BlockInt8Quantizer):
        raise ValueError(
            "fused_sgd/fused_adam need the block-int8 codec whose layout "
            "the kernels consume: pass compression=Compression.int8 (or "
            "ErrorFeedback(Compression.int8)); for other codecs use "
            f"spec.to_optax() with DistributedOptimizer (got {codec!r})")
    if op not in (Sum, ReduceOp.AVERAGE):
        raise ValueError(f"fused apply supports Sum/Average, got {op}")
    block = codec.block_size
    interp = codec.interpret
    use_mom = spec.kind == "adam" or spec.momentum != 0.0

    def init_fn(params):
        def zeros(p):
            if not _is_float(p):
                return None
            _, nb = _leaf_meta(p, block)
            return jnp.zeros((nb, block), jnp.float32)

        mom = _tree.tree_map(zeros, params) if use_mom else \
            _tree.tree_map(lambda p: None, params)
        vel = _tree.tree_map(zeros, params) if spec.kind == "adam" else \
            _tree.tree_map(lambda p: None, params)
        res = _tree.tree_map(zeros, params) if ef else \
            _tree.tree_map(lambda p: None, params)
        return FusedOptState(count=jnp.zeros((), jnp.int32), mom=mom,
                             vel=vel, residual=res)

    def update_fn(updates, state, params=None):
        del params
        from horovod_tpu.ops.pallas_quantize import (
            block_dequantize, block_quantize_ef, fused_adam_apply,
            fused_sgd_apply)

        t = state.count + 1
        tf = t.astype(jnp.float32)
        if spec.kind == "adam":
            bc1 = 1.0 - jnp.float32(spec.b1) ** tf
            bc2 = 1.0 - jnp.float32(spec.b2) ** tf

        leaves, treedef = _tree.tree_flatten(updates)
        flat_mom = treedef.flatten_up_to(state.mom)
        flat_vel = treedef.flatten_up_to(state.vel)
        flat_res = treedef.flatten_up_to(state.residual)

        traced = _is_traced(updates)
        # a live named axis spans DEVICES within one process, so it wins
        # over the process count; eager needs multiple processes; the
        # rest (global-SPMD jit, single process) is identity sync
        axis_regime = traced and axis_name is not None
        eager = (not traced) and size() > 1
        identity_sync = not axis_regime and not eager

        # pass 1: quantize (+EF residual) every float leaf
        quantized = []  # (vals, scales) or None per leaf
        new_res = list(flat_res)
        for i, g in enumerate(leaves):
            if not _is_float(g):
                quantized.append(None)
                continue
            blocks = _to_blocks(g, block)
            if ef and flat_res[i] is not None:
                blocks = blocks + flat_res[i]
            vals, scales, res = block_quantize_ef(blocks, interpret=interp)
            quantized.append((vals, scales))
            if ef:
                new_res[i] = res

        # pass 2 (non-identity regimes): materialize the synced, still
        # blocked fp32 gradients
        synced_blocks = [None] * len(leaves)
        if not identity_sync:
            if eager:
                from horovod_tpu.train.optimizer import \
                    _eager_allreduce_tree
                qdq = [leaves[i] if q is None else
                       _from_blocks(block_dequantize(q[0], q[1],
                                                     interpret=interp),
                                    leaves[i])
                       for i, q in enumerate(quantized)]
                synced = _eager_allreduce_tree(
                    _tree.tree_unflatten(treedef, qdq), op, process_set,
                    codec, 1.0, 1.0)
                synced_blocks = [
                    None if q is None else _to_blocks(s, block)
                    for q, s in zip(quantized,
                                    _tree.tree_leaves(synced))]
            else:  # traced with a live named axis
                from horovod_tpu.ops.mesh_collectives import preduce
                synced_blocks = [
                    None if q is None else
                    preduce(block_dequantize(q[0], q[1], interpret=interp),
                            axis_name, op)
                    for q in quantized]

        # pass 3: fused (or blocked-XLA) optimizer apply
        out = []
        new_mom = list(flat_mom)
        new_vel = list(flat_vel)
        for i, g in enumerate(leaves):
            q = quantized[i]
            if q is None:
                out.append(jnp.zeros_like(g))
                continue
            if spec.kind == "sgd":
                mom_i = flat_mom[i] if use_mom else None
                if identity_sync:
                    delta, nm = fused_sgd_apply(
                        q[0], q[1], mom_i, spec.lr, spec.momentum,
                        interpret=interp)
                else:
                    h = jnp.stack([jnp.float32(spec.lr),
                                   jnp.float32(spec.momentum)])
                    delta, nm = _apply_sgd_blocks(h, synced_blocks[i],
                                                  mom_i)
                if use_mom:
                    new_mom[i] = nm
            else:
                if identity_sync:
                    delta, nm, nv = fused_adam_apply(
                        q[0], q[1], flat_mom[i], flat_vel[i], spec.lr,
                        spec.b1, spec.b2, spec.eps, bc1, bc2,
                        interpret=interp)
                else:
                    h = jnp.stack([jnp.float32(spec.lr),
                                   jnp.float32(spec.b1),
                                   jnp.float32(spec.b2),
                                   jnp.float32(spec.eps), bc1, bc2])
                    delta, nm, nv = _apply_adam_blocks(
                        h, synced_blocks[i], flat_mom[i], flat_vel[i])
                new_mom[i], new_vel[i] = nm, nv
            out.append(_from_blocks(delta, g))

        new_state = FusedOptState(
            count=t,
            mom=_tree.tree_unflatten(treedef, new_mom),
            vel=_tree.tree_unflatten(treedef, new_vel),
            residual=_tree.tree_unflatten(treedef, new_res))
        return _tree.tree_unflatten(treedef, out), new_state

    return optax.GradientTransformation(init_fn, update_fn)


def _apply_sgd_blocks(h, g_blocks, mom):
    """optax.sgd update on already-dequantized fp32 blocks (the
    non-identity-sync regimes, where the reduction had to densify)."""
    if mom is None:
        return -h[0] * g_blocks, None
    m = g_blocks + h[1] * mom
    return -h[0] * m, m


def _apply_adam_blocks(h, g_blocks, m, v):
    m = h[1] * m + (1.0 - h[1]) * g_blocks
    v = h[2] * v + (1.0 - h[2]) * g_blocks * g_blocks
    delta = -h[0] * (m / h[4]) / (jnp.sqrt(v / h[5]) + h[3])
    return delta, m, v
