"""Backprop/collective overlap engine: software-pipelined, bucketed
gradient reduction for traced (shard_map) training loops.

The reference's whole reason to exist is that gradient reduction runs
WHILE autograd is still producing later gradients (PAPER.md: the
background thread fuses and dispatches collectives mid-backward). Our
traced mesh path used to reduce the entire gradient pytree in one shot
after backward completed — every byte of collective time fully exposed.
This module restructures microbatch accumulation into a software
pipeline:

    iteration k:   issue reduce of microbatch k−1's gradients (bucketed)
                   run microbatch k's forward+backward

Inside ``lax.scan`` the bucket collectives for iteration k−1 have no
data dependency on iteration k's backward, so XLA's latency-hiding
scheduler overlaps them — the compiler-scheduled analog of the
reference's background fusion thread. Reduction is linear, so
``reduce(Σₖ gₖ) == Σₖ reduce(gₖ)`` and the pipelined result matches the
reduce-at-the-end result up to fp reassociation (bit-exact quantized
parity is NOT preserved — each microbatch quantizes separately — which
is why the parity tests compare loss trajectories under int8+EF).

Buckets come from :mod:`horovod_tpu.train.buckets` (reverse
registration order, fusion-threshold byte budget); each bucket is one
``psum``/``pmean`` — or reduce_scatter→quantize→allgather when a
quantizer is given (EQuARX shape, ``preduce_quantized``), or a chunked
``ppermute`` ring (``pring_allreduce``) for the large-bucket case.

With accumulation off (one microbatch) there is nothing to overlap
with: the exact numerics-parity fallback computes the gradients and
then syncs them, identical to the serialized path.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu._compat import axis_size
from horovod_tpu.ops.reduce_op import Average, ReduceOp, Sum
from horovod_tpu.train.buckets import (BucketPlan, pack, plan_buckets,
                                       unpack)

_tree = jax.tree_util


def _tree_add(a, b):
    return _tree.tree_map(jnp.add, a, b)


def resolve_small_floor(small_floor: Optional[int] = None) -> int:
    """Effective small-bucket latency floor in bytes: explicit argument >
    ``HVD_TPU_SMALL_BUCKET_FLOOR`` (``Config.small_bucket_floor``).
    0 disables the latency path."""
    if small_floor is not None:
        return max(0, int(small_floor))
    from horovod_tpu.common.config import get_config
    return max(0, get_config().small_bucket_floor)


def bucketed_grad_sync(grads, axis_name: str,
                       plan: Optional[BucketPlan] = None,
                       bucket_bytes: Optional[int] = None,
                       op: ReduceOp = Average,
                       compression=None,
                       ring: bool = False,
                       algorithm: Optional[str] = None,
                       topology=None,
                       small_floor: Optional[int] = None):
    """Reduce a gradient pytree along ``axis_name`` bucket by bucket.

    Call inside ``shard_map`` (a live named axis). Each bucket's leaves
    are packed into one flat vector and reduced with ONE collective,
    selected by ``algorithm``:

    * ``"psum"`` (default) — ``psum``/``pmean``; with ``compression`` (a
      :class:`~horovod_tpu.compression.quantizers.Quantizer`) the EQuARX
      ``reduce_scatter → quantize → all_gather`` path.
    * ``"ring"`` — the chunked ``ppermute`` ring
      (:func:`ops.mesh_collectives.pring_allreduce`); ``ring=True`` is
      the back-compat spelling. No compression seam (per-hop
      requantization would accumulate error).
    * ``"hier"`` — the topology-aware two-level path
      (:func:`ops.mesh_collectives.phier_allreduce`): intra-host
      reduce_scatter → inter-host allreduce → intra-host allgather,
      with ``compression`` applied to the inter-host hop only.
      ``topology`` (a :class:`~horovod_tpu.common.topology.MeshTopology`)
      defaults to :func:`~horovod_tpu.common.topology.detect_topology`
      over the axis size; a non-hierarchical topology degrades to psum.

    ``small_floor`` (bytes; default ``HVD_TPU_SMALL_BUCKET_FLOOR``):
    buckets under the floor skip quantization and ring/hierarchical
    chunking and take one dense ``psum`` — the latency-optimized
    small-tensor path (arxiv 1909.09756). Emitting one independent
    collective per bucket — instead of one per leaf or one for the
    whole tree — is what gives XLA's scheduler units it can overlap
    with compute.

    Quantized, ring and hierarchical paths support Sum/Average only.
    """
    from horovod_tpu.ops.mesh_collectives import (phier_allreduce, preduce,
                                                  preduce_quantized,
                                                  pring_allreduce)
    algo = algorithm or ("ring" if ring else "psum")
    if algo not in ("psum", "ring", "hier"):
        raise ValueError(
            f"unknown bucket algorithm {algorithm!r}; expected "
            "psum | ring | hier")
    if algo == "ring" and compression is not None:
        raise ValueError(
            "ring allreduce has no compression seam (per-hop "
            "requantization accumulates error); use algorithm='psum' or "
            "'hier' with a quantizer")
    leaves, treedef = _tree.tree_flatten(grads)
    if not leaves:
        return grads
    if plan is None:
        plan = plan_buckets(leaves, bucket_bytes)
    n = axis_size(axis_name)
    floor = resolve_small_floor(small_floor)
    if algo == "hier":
        if topology is None:
            from horovod_tpu.common.topology import detect_topology
            topology = detect_topology(n=n)
        if not topology.is_hierarchical:
            algo = "psum"  # flat topology: the two-level path IS psum
    out: list = [None] * len(leaves)
    for bucket in plan.buckets:
        small = floor > 0 and bucket.nbytes < floor
        if small or (algo == "psum" and compression is None):
            vec = pack(leaves, bucket)
            reduced = preduce(vec, axis_name, op)
        elif algo == "psum":
            if op not in (Sum, ReduceOp.AVERAGE):
                raise ValueError(
                    f"quantized bucket sync supports Sum/Average, got {op}")
            vec = pack(leaves, bucket, pad_to=n)
            reduced = preduce_quantized(vec, axis_name, compression, op)
        elif algo == "ring":
            vec = pack(leaves, bucket)
            reduced = pring_allreduce(vec, axis_name, op)
        else:  # hier
            vec = pack(leaves, bucket)
            reduced = phier_allreduce(vec, axis_name, topology, op,
                                      inter_codec=compression)
        for i, leaf in zip(bucket.indices,
                           unpack(reduced, bucket, leaves)):
            out[i] = leaf
    return _tree.tree_unflatten(treedef, out)


def pipelined_accumulate(grad_fn: Callable, params,
                         microbatches, *,
                         axis_name: str,
                         op: ReduceOp = Average,
                         plan: Optional[BucketPlan] = None,
                         bucket_bytes: Optional[int] = None,
                         compression=None,
                         ring: bool = False,
                         algorithm: Optional[str] = None,
                         topology=None,
                         small_floor: Optional[int] = None,
                         overlap: bool = True,
                         sync: bool = True,
                         microbatch_mean: bool = True
                         ) -> Tuple[jax.Array, Any]:
    """Microbatch-accumulated, cross-replica-reduced gradients with the
    bucket collectives software-pipelined one iteration behind their
    production.

    ``grad_fn(params, microbatch) -> (loss, grads)`` runs one
    microbatch's forward+backward; ``microbatches`` is a pytree whose
    leaves carry the microbatch count as their leading axis. Returns
    ``(mean_loss, reduced_grads)`` where the gradients are reduced over
    ``axis_name`` (per ``op``) and averaged over microbatches (set
    ``microbatch_mean=False`` to keep the sum).

    ``overlap=True`` (default): scan iteration k issues microbatch
    k−1's bucket reductions and runs microbatch k's backward — no data
    dependency between the two, so XLA overlaps them. ``overlap=False``
    is the serialized comparator: identical numerics, but an
    ``optimization_barrier`` pins every reduction onto the critical
    path before the next backward may start (this is the
    bucket-pipelining-off baseline the overlap bench measures against).
    ``sync=False`` skips reduction entirely — the compute-only baseline
    for exposed-communication attribution.

    With ONE microbatch the pipeline degenerates to the exact
    numerics-parity fallback: backward, then the same bucketed sync —
    there is no second backward to hide the collectives behind.
    """
    sizes = {x.shape[0] for x in _tree.tree_leaves(microbatches)}
    if len(sizes) != 1:
        raise ValueError(
            f"microbatch leaves disagree on the leading axis: {sizes}")
    n_micro = sizes.pop()
    if n_micro < 1:
        raise ValueError("need at least one microbatch")

    def _sync(grads):
        if not sync:
            return grads
        return bucketed_grad_sync(grads, axis_name, plan=plan,
                                  bucket_bytes=bucket_bytes, op=op,
                                  compression=compression, ring=ring,
                                  algorithm=algorithm, topology=topology,
                                  small_floor=small_floor)

    def _take(k):
        return _tree.tree_map(lambda x: x[k], microbatches)

    scale = (1.0 / n_micro) if microbatch_mean else 1.0

    if n_micro == 1:
        loss, grads = grad_fn(params, _take(0))
        return loss, _sync(grads)

    loss0, g0 = grad_fn(params, _take(0))
    rest = _tree.tree_map(lambda x: x[1:], microbatches)
    zeros = _tree.tree_map(jnp.zeros_like, g0)

    if overlap:
        def body(carry, mb):
            pending, acc = carry
            # no data dependency between these two lines: the bucket
            # collectives of the PREVIOUS microbatch overlap this one's
            # forward+backward on the XLA schedule
            reduced = _sync(pending)
            loss, g = grad_fn(params, mb)
            return (g, _tree_add(acc, reduced)), loss

        (last, acc), losses = lax.scan(body, (g0, zeros), rest)
        total = _tree_add(acc, _sync(last))
    else:
        acc0 = _sync(g0)

        def body(carry, mb):
            acc = carry
            # serialize: the next backward's params are gated behind the
            # finished reduction, putting every collective on the
            # critical path (numerics unchanged — this is a pure
            # scheduling barrier)
            p_gated, acc = lax.optimization_barrier((params, acc))
            loss, g = grad_fn(p_gated, mb)
            return _tree_add(acc, _sync(g)), loss

        total, losses = lax.scan(body, acc0, rest)

    mean_loss = (loss0 + jnp.sum(losses)) / n_micro
    if scale != 1.0:
        total = _tree.tree_map(lambda x: x * scale, total)
    return mean_loss, total


def make_overlap_train_step(loss_fn: Callable, optimizer, mesh,
                            axis_name: str = "dp", *,
                            n_micro: int = 1,
                            op: ReduceOp = Average,
                            bucket_bytes: Optional[int] = None,
                            compression=None,
                            ring: bool = False,
                            algorithm: Optional[str] = None,
                            topology=None,
                            small_floor: Optional[int] = None,
                            overlap: bool = True,
                            sync: bool = True,
                            donate: bool = True,
                            autotune=None,
                            guard=None) -> Callable:
    """jit-compiled data-parallel train step with pipelined bucket
    overlap: ``shard_map`` over ``mesh[axis_name]``, ``n_micro``
    microbatches split from the batch's leading axis, gradients reduced
    via :func:`pipelined_accumulate`, then ``optimizer`` applied.

    ``loss_fn(params, batch) -> scalar loss``. The returned callable is
    ``step(params, opt_state, batch) -> (params, opt_state, loss)``
    with the batch's leading axis sharded over ``axis_name`` and
    divisible by ``n_micro`` per shard. Keyword knobs mirror
    :func:`pipelined_accumulate` (see docs/PERF.md "Overlap &
    bucketing").

    ``autotune`` hands the communication knobs (``bucket_bytes``,
    ``algorithm``, ``compression`` codec, ``small_floor``) to the online
    plan search (docs/PERF.md "Autotuning"): pass ``True`` for the
    default search, or a :class:`horovod_tpu.train.autotune.AutotuneOptions`.
    The returned step then measures candidate plans during early steps,
    locks the winner, and persists it to the plan cache; explicit values
    for the tuned knobs become the search's baseline candidate.

    ``guard`` controls the numeric guardrail
    (:mod:`horovod_tpu.train.guard`): ``None`` reads ``HVD_TPU_GUARD``
    (default ON — a non-finite or over-``HVD_TPU_GUARD_MAX_NORM``
    gradient skips the step with the optimizer state preserved, counted
    on ``hvd_guard_skipped_steps_total``), ``False`` disables (the
    exact pre-guard step, three outputs, no wrapper), ``True`` or a
    :class:`~horovod_tpu.train.guard.GuardSpec` pins it.  With the
    guard on, the returned callable is a
    :class:`~horovod_tpu.train.guard.GuardedStep` — same call surface,
    attributes forwarded — and the chaos ``grad`` seam (when armed) is
    compiled into the step.
    """
    import optax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu._compat import shard_map

    if autotune is None:
        # HVD_TPU_AUTOTUNE_MESH turns the search on fleet-wide without
        # touching call sites; an explicit autotune=False still wins
        from horovod_tpu.common.config import get_config
        autotune = get_config().autotune_mesh or None
    if autotune:
        from horovod_tpu.train.autotune import make_autotuned_train_step
        return make_autotuned_train_step(
            loss_fn, optimizer, mesh, axis_name, autotune=autotune,
            n_micro=n_micro, op=op, bucket_bytes=bucket_bytes,
            compression=compression, ring=ring, algorithm=algorithm,
            topology=topology, small_floor=small_floor, overlap=overlap,
            sync=sync, donate=donate, guard=guard)

    from horovod_tpu.train import guard as guard_mod
    gspec = guard_mod.resolve_spec(guard)
    grad_fn = jax.value_and_grad(loss_fn)

    def _loss_and_grads(params, batch):
        def micro_grad(p, mb):
            return grad_fn(p, mb)

        micro = _tree.tree_map(
            lambda x: x.reshape((n_micro, x.shape[0] // n_micro)
                                + x.shape[1:]), batch)
        return pipelined_accumulate(
            micro_grad, params, micro, axis_name=axis_name, op=op,
            bucket_bytes=bucket_bytes, compression=compression, ring=ring,
            algorithm=algorithm, topology=topology, small_floor=small_floor,
            overlap=overlap, sync=sync)

    if not gspec.enabled:
        def shard_body(params, opt_state, batch):
            loss, grads = _loss_and_grads(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, lax.pmean(loss, axis_name)

        wrapped = shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(), P(), P(axis_name)),
            out_specs=(P(), P(), P()),
            check_vma=False)
        return jax.jit(wrapped, donate_argnums=(0, 1) if donate else ())

    # guard on: the body grows the chaos injection seam (data-driven —
    # compiled in only when a grad fault plan is armed for this rank)
    # and a 4th output, the guard verdict, which the GuardedStep wrapper
    # strips and observes one step late
    from horovod_tpu import chaos
    inject = chaos.grad_rules_armed()

    def shard_body(params, opt_state, batch, inj):
        loss, grads = _loss_and_grads(params, batch)
        if inject:
            grads = guard_mod.apply_injection(grads, inj)
        params, opt_state, ok = guard_mod.guarded_apply(
            optimizer, grads, opt_state, params, gspec)
        return params, opt_state, lax.pmean(loss, axis_name), ok

    wrapped = shard_map(
        shard_body, mesh=mesh,
        in_specs=(P(), P(), P(axis_name), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False)
    fn = jax.jit(wrapped, donate_argnums=(0, 1) if donate else ())
    return guard_mod.GuardedStep(fn, gspec, inject=inject)
