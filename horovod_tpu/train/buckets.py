"""Gradient bucket planner: byte-budgeted partitions of a gradient pytree.

The reference overlaps gradient reduction with backward compute by
fusing ready tensors into a bounded buffer and dispatching it while
autograd is still producing later gradients (``fusion_buffer_manager.h``,
PAPER.md background thread). The JAX analog needs the partition decided
AHEAD of time — traced programs can't grow buffers dynamically — so this
module plans it once per (tree structure, budget): leaves are walked in
REVERSE registration order (output-side layers produce their gradients
first under reverse-mode AD, exactly the order the reference's hooks see
them) and greedily packed into buckets of at most ``bucket_bytes``.

The byte budget intentionally reuses the engine's fusion-threshold
semantics (``HVD_TPU_FUSION_THRESHOLD`` → ``Config.fusion_threshold_bytes``,
64 MiB like the C++ core) unless overridden by ``HVD_TPU_BUCKET_BYTES``
or an explicit argument — so the eager TCP path (which fuses per cycle in
C++) and the traced mesh path (which packs per bucket here) agree on what
"one unit of communication" means.

Consumers:

* :mod:`horovod_tpu.train.overlap` — per-bucket reduce_scatter→allgather
  pipelined against the next microbatch's backward (traced regimes);
* :mod:`horovod_tpu.train.optimizer` — per-bucket
  ``grouped_allreduce_async`` on the eager wire, so bucket ``b+1``'s
  codec/enqueue overlaps bucket ``b``'s wire time.

Planning is pure metadata (shapes/dtypes only — works on
``jax.ShapeDtypeStruct`` trees and tracers alike) and cached per
(structure, budget); ``pack``/``unpack`` are the matching runtime
helpers that concatenate a bucket's leaves into one flat fp32 vector and
split it back.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class Bucket(NamedTuple):
    """One communication unit: ``indices`` are positions into the
    tree_flatten leaf list (ascending within the bucket), ``nbytes`` the
    payload size at the leaves' own dtypes."""

    indices: Tuple[int, ...]
    nbytes: int


class BucketPlan(NamedTuple):
    """Buckets in ISSUE order (reverse registration: bucket 0 holds the
    LAST-registered leaves — the first gradients backprop produces)."""

    buckets: Tuple[Bucket, ...]
    total_bytes: int

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)


def resolve_bucket_bytes(bucket_bytes: Optional[int] = None) -> int:
    """Effective byte budget: explicit argument > ``HVD_TPU_BUCKET_BYTES``
    (``Config.bucket_bytes``) > the engine's fusion threshold."""
    if bucket_bytes is not None:
        return max(1, int(bucket_bytes))
    from horovod_tpu.common.config import get_config
    cfg = get_config()
    if cfg.bucket_bytes > 0:
        return cfg.bucket_bytes
    return max(1, cfg.fusion_threshold_bytes)


def _leaf_nbytes(leaf) -> int:
    shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
    dtype = np.dtype(getattr(leaf, "dtype", None) or np.asarray(leaf).dtype)
    return int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape \
        else dtype.itemsize


@functools.lru_cache(maxsize=256)
def _plan_cached(sizes: Tuple[int, ...], budget: int,
                 reverse: bool) -> BucketPlan:
    order = range(len(sizes) - 1, -1, -1) if reverse else range(len(sizes))
    buckets = []
    cur: list = []
    cur_bytes = 0
    for i in order:
        nb = sizes[i]
        # a leaf larger than the whole budget still gets exactly one
        # bucket (the engine's fusion buffer has the same overflow rule:
        # an oversized tensor is its own execution unit)
        if cur and cur_bytes + nb > budget:
            buckets.append(Bucket(tuple(sorted(cur)), cur_bytes))
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        buckets.append(Bucket(tuple(sorted(cur)), cur_bytes))
    return BucketPlan(tuple(buckets), sum(sizes))


def plan_buckets(tree: Any, bucket_bytes: Optional[int] = None,
                 reverse: bool = True) -> BucketPlan:
    """Partition ``tree``'s leaves into byte-budgeted buckets.

    ``tree`` may hold arrays, tracers, or ``jax.ShapeDtypeStruct``s —
    only shapes/dtypes are read. Leaves are taken in reverse
    registration order by default (tiny tensors coalesce with their
    neighbors until the running total would exceed the budget); a leaf
    bigger than the budget forms its own bucket. Records the plan on
    the overlap metrics gauges (``docs/OBSERVABILITY.md``).
    """
    leaves = jax.tree_util.tree_leaves(tree)
    budget = resolve_bucket_bytes(bucket_bytes)
    plan = _plan_cached(tuple(_leaf_nbytes(l) for l in leaves), budget,
                        bool(reverse))
    record_plan(plan)
    return plan


def record_plan(plan: BucketPlan) -> None:
    """Surface the active plan on /metrics (PR-1 registry): bucket count
    and total payload bytes."""
    from horovod_tpu.metrics.registry import default_registry
    reg = default_registry()
    reg.gauge("hvd_overlap_bucket_count",
              help="gradient buckets in the active overlap plan"
              ).set(plan.num_buckets)
    reg.gauge("hvd_overlap_bucket_bytes",
              help="total gradient payload bytes in the active plan"
              ).set(plan.total_bytes)


# ---------------------------------------------------------------------------
# Runtime pack/unpack (traced-safe)
# ---------------------------------------------------------------------------

def pack(leaves: Sequence, bucket: Bucket, pad_to: int = 1) -> jax.Array:
    """Concatenate ``bucket``'s leaves into one flat vector, zero-padded
    to a ``pad_to`` multiple (collective divisibility: pass the
    mesh-axis size — or axis*block for the quantized path).

    The vector's dtype is the bucket's widest member dtype
    (``jnp.result_type``), NOT a forced fp32: an all-bf16 gradient
    bucket moves bf16 over the interconnect — the same in-wire dtype
    XLA's sharding-derived reduction would use — instead of paying 2x
    the bytes this subsystem exists to save. Mixed buckets promote to
    the widest member (bf16+fp32 → fp32)."""
    dtype = jnp.result_type(*(leaves[i].dtype for i in bucket.indices))
    vec = jnp.concatenate(
        [jnp.ravel(leaves[i]).astype(dtype) for i in bucket.indices])
    pad = (-vec.size) % max(1, pad_to)
    if pad:
        vec = jnp.concatenate([vec, jnp.zeros((pad,), dtype)])
    return vec


def unpack(vec: jax.Array, bucket: Bucket, like: Sequence) -> list:
    """Split a packed (possibly padded) vector back into ``bucket``'s
    leaves with their original shapes/dtypes. Returns leaves in
    ``bucket.indices`` order."""
    out = []
    offset = 0
    for i in bucket.indices:
        ref = like[i]
        n = int(np.prod(ref.shape, dtype=np.int64)) if ref.shape else 1
        out.append(jax.lax.dynamic_slice_in_dim(vec, offset, n)
                   .reshape(ref.shape).astype(ref.dtype))
        offset += n
    return out
