"""Training-loop callbacks.

Reference: ``horovod/_keras/callbacks.py`` — ``BroadcastGlobalVariables``
(:23-47), ``MetricAverageCallback`` (:49-93), ``LearningRateWarmupCallback``
(:118-192). The reference hooks Keras; here the hooks are framework-neutral
callables for JAX training loops (works with any loop that calls
``on_train_begin`` / ``on_epoch_end``-style hooks or uses them directly).
"""

from __future__ import annotations

import re
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from horovod_tpu.common.basics import rank, size
from horovod_tpu.common.logging import get_logger
from horovod_tpu.metrics.registry import Gauge, Registry, default_registry
from horovod_tpu.ops import collectives as C
from horovod_tpu.ops.reduce_op import Average


class BroadcastGlobalVariablesCallback:
    """Broadcast params/opt-state from root at training start (reference:
    ``BroadcastGlobalVariablesCallbackImpl:23-47``)."""

    def __init__(self, root_rank: int = 0) -> None:
        self.root_rank = root_rank

    def on_train_begin(self, params, opt_state=None):
        from horovod_tpu.train.optimizer import (broadcast_optimizer_state,
                                                 broadcast_parameters)
        params = broadcast_parameters(params, self.root_rank)
        if opt_state is not None:
            opt_state = broadcast_optimizer_state(opt_state, self.root_rank)
            return params, opt_state
        return params


class MetricAverageCallback:
    """Average logged metrics across workers at epoch end (reference:
    ``MetricAverageCallbackImpl:49-93``)."""

    def on_epoch_end(self, logs: Dict[str, Any]) -> Dict[str, Any]:
        if size() == 1:
            return dict(logs)
        out = {}
        for k, v in logs.items():
            if isinstance(v, (int, float, np.floating, np.integer)):
                red = C.allreduce(np.asarray([float(v)], np.float64),
                                  op=Average, name=f"metric.{k}")
                out[k] = float(np.asarray(red)[0])
            else:
                out[k] = v
        return out


class StepTimer:
    """Step-time + throughput recorder feeding the metrics registry.

    Records every step into ``hvd_step_time_seconds`` (log-scale
    histogram), counts steps and processed units (images / tokens /
    sequences — your choice of ``unit``), and keeps live gauges for
    units/s and, when FLOPs are known, MFU. Everything it writes appears
    on the worker's ``/metrics`` endpoint and in
    ``hvd.metrics_snapshot()["registry"]``.

    Use directly::

        timer = StepTimer(unit="images")
        for batch in data:
            with timer.step(units=batch_size):
                state, loss = train_step(state, batch)
            # or: timer.start_step(); ...; timer.end_step(units=...)

    ``flops_per_step`` is per-device FLOPs for ONE step (see
    :func:`horovod_tpu.metrics.mfu.hlo_flops_per_device`); the peak is
    looked up from the local chip on first use.
    """

    def __init__(self, unit: str = "examples",
                 flops_per_step: Optional[float] = None,
                 registry: Optional[Registry] = None) -> None:
        reg = registry or default_registry()
        self._reg = reg
        self.unit = unit
        # "tokens/s" or "img-sec" would break the Prometheus metric-name
        # charset and take the whole /metrics response down with it
        metric_unit = re.sub(r"[^a-zA-Z0-9_]", "_", unit)
        self.step_time = reg.histogram(
            "hvd_step_time_seconds", help="training step wall time")
        self.steps = reg.counter("hvd_steps_total",
                                 help="training steps completed")
        self.units = reg.counter(f"hvd_{metric_unit}_total",
                                 help=f"{unit} processed")
        self.throughput = reg.gauge(
            f"hvd_{metric_unit}_per_second",
            help=f"{unit}/s over the last step (sum across workers)",
            agg="sum")
        # registered lazily on the first computed MFU: an eager gauge
        # would export 0.0 from workers that never compute MFU and drag
        # the mean-merged fleet value toward zero
        self.mfu_gauge: Optional[Gauge] = None
        self.flops_per_step = flops_per_step
        self._peak: Any = _UNSET
        self._t0: Optional[float] = None
        self.last_step_seconds: Optional[float] = None
        # MFU actually computed for the most recent step, None when it
        # could not be (flops or device peak unknown) — the gauge's 0.0
        # default is indistinguishable from a measured zero
        self.last_mfu: Optional[float] = None

    def set_flops_per_step(self, flops: Optional[float]) -> None:
        self.flops_per_step = flops

    def start_step(self) -> None:
        self._t0 = time.perf_counter()
        from horovod_tpu.diagnostics.flight_recorder import record_event
        # +1: number the step being ENTERED, matching the post-increment
        # number its step_end will carry (begin/end pairs must agree)
        step_no = int(self.steps.value) + 1
        record_event("step_begin", step=step_no)
        # deep-profiling seam (docs/OBSERVABILITY.md "Deep profiling"):
        # a pending capture request opens its jax.profiler window at
        # this step boundary; cheap no-op otherwise
        from horovod_tpu import profiling
        profiling.on_step_begin(step_no)
        # goodput ledger (docs/OBSERVABILITY.md "Goodput ledger"): the
        # step envelope is the ledger's spine — begin/end bracket the
        # in-step account, the gap between them is the out-of-step one
        from horovod_tpu.metrics import goodput
        goodput.note_step_begin()

    def end_step(self, units: float = 0.0) -> Optional[float]:
        """Close the step opened by :meth:`start_step`; returns the step
        seconds (None if no step was open)."""
        if self._t0 is None:
            return None
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self.last_step_seconds = dt
        self.step_time.observe(dt)
        self.steps.inc()
        # a completed step IS forward progress: feed the hang watchdog
        # and the flight recorder (docs/OBSERVABILITY.md)
        step_no = int(self.steps.value)
        from horovod_tpu.diagnostics.flight_recorder import record_event
        from horovod_tpu.diagnostics.watchdog import notify_progress
        record_event("step_end", step=step_no, seconds=round(dt, 6))
        notify_progress(step_no)
        # step-aligned history: the bounded ring (always) + the
        # HVD_TPU_OBS_DIR JSONL (when set) — docs/OBSERVABILITY.md
        # "Step time-series history"
        from horovod_tpu.metrics import timeseries
        timeseries.record_step(step_no, dt, units)
        # deep-profiling seam: close an active capture window when its
        # step budget is spent, and sample the HBM gauges; a completed
        # step also closes the re-mesh timeline's first_step phase
        from horovod_tpu import profiling
        profiling.on_step_end(step_no)
        from horovod_tpu.elastic import remesh
        remesh.note_step_end(step_no)
        from horovod_tpu.metrics import goodput
        goodput.note_step_end(dt)
        if units:
            self.units.inc(units)
            if dt > 0:
                self.throughput.set(units / dt)
        self.last_mfu = None
        if self.flops_per_step and dt > 0:
            if self._peak is _UNSET:
                from horovod_tpu.metrics.mfu import device_peak_flops
                try:
                    self._peak = device_peak_flops()
                except Exception:
                    self._peak = None
            if self._peak:
                self.last_mfu = self.flops_per_step / dt / self._peak
                if self.mfu_gauge is None:
                    self.mfu_gauge = self._reg.gauge(
                        "hvd_mfu",
                        help="model FLOPs utilization of the last step",
                        agg="mean")
                self.mfu_gauge.set(self.last_mfu)
        return dt

    class _StepCtx:
        def __init__(self, timer: "StepTimer", units: float) -> None:
            self._timer = timer
            self._units = units

        def __enter__(self):
            self._timer.start_step()
            return self._timer

        def __exit__(self, exc_type, exc, tb):
            if exc_type is None:
                self._timer.end_step(self._units)
            else:
                self._timer._t0 = None  # failed step: don't pollute stats
            return False

    def step(self, units: float = 0.0) -> "StepTimer._StepCtx":
        return StepTimer._StepCtx(self, units)


_UNSET = object()


class TelemetryCallback:
    """Train-loop hook bundle around :class:`StepTimer`.

    Call ``on_step_begin()`` / ``on_step_end()`` from any loop (same hook
    style as the other callbacks in this module). FLOPs for MFU are
    resolved lazily on the first completed step from ``lowerable`` — a
    zero-arg callable returning ``(jitted, args)`` exactly like
    ``bench.py``'s ``_Run.lowerable`` — via the compiled executable's
    cost analysis (:func:`horovod_tpu.metrics.mfu.hlo_flops_per_device`);
    a failure there just leaves MFU unset, never breaks the loop.

    ``log_every_n_steps`` > 0 logs a one-line telemetry summary (step
    time, units/s, MFU) through the rank-tagged logger.

    ``profile_steps`` > 0 schedules a ProfileManager device-trace
    capture of the FIRST ``profile_steps`` training steps
    (docs/OBSERVABILITY.md "Deep profiling"); independent of it, the
    anomaly engine can fire captures later in the run
    (``HVD_TPU_PROFILE_ON_ANOMALY``).

    Creating the callback also arms the process-wide hang watchdog
    (``HVD_TPU_WATCHDOG_SECONDS``, default 600; 0 disarms): if no step
    completes for that long, an autopsy bundle is written —
    docs/OBSERVABILITY.md "Flight recorder & hang autopsy".
    """

    def __init__(self, units_per_step: float = 0.0,
                 unit: str = "examples",
                 lowerable: Optional[Callable[[], tuple]] = None,
                 flops_per_step: Optional[float] = None,
                 hlo_flops_factor: int = 1,
                 log_every_n_steps: int = 0,
                 profile_steps: int = 0,
                 registry: Optional[Registry] = None) -> None:
        self.timer = StepTimer(unit=unit, flops_per_step=flops_per_step,
                               registry=registry)
        self.units_per_step = units_per_step
        self._lowerable = lowerable
        self._hlo_factor = hlo_flops_factor
        self._log_every = log_every_n_steps
        self._steps = 0
        # armed-by-default: a training loop with telemetry gets hang
        # autopsies for free (None when WATCHDOG_SECONDS=0).  Only for
        # an INITIALIZED process: a callback constructed without
        # hvd.init (unit tests, dry imports) has no world to autopsy,
        # and a leaked 600s daemon in a long pytest process would
        # eventually fire mid-suite — the false positive the acceptance
        # criteria forbid.
        from horovod_tpu.common.basics import is_initialized
        from horovod_tpu.diagnostics.watchdog import ensure_watchdog
        self.watchdog = ensure_watchdog() if is_initialized() else None
        # online anomaly engine (docs/OBSERVABILITY.md "Anomaly
        # engine"; HVD_TPU_ANOMALY=0 disables): every completed step
        # feeds the drift detectors — a degradation is flagged as an
        # hvd_anomaly_total{kind} counter + flight event while the job
        # still runs, and lands in any later autopsy bundle's summary
        from horovod_tpu.metrics.anomaly import default_engine
        self.anomaly_engine = default_engine()
        # compile observability rides every telemetry loop (idempotent;
        # HVD_TPU_COMPILE_METRICS=0 disables)
        from horovod_tpu.profiling import compile_watch
        compile_watch.ensure_installed()
        if profile_steps > 0:
            # armed now, opens at the first step boundary
            from horovod_tpu.profiling import default_manager
            default_manager().request_capture(steps=profile_steps,
                                              reason="telemetry")

    def on_train_begin(self, *args, **kwargs):
        return args[0] if len(args) == 1 else (args or None)

    def on_step_begin(self) -> None:
        self.timer.start_step()
        # chaos `step` seam (docs/CHAOS.md): rank kill/stall schedules
        # key on the step counter; dead when no fault plan is armed.
        # AFTER start_step: an injected stall must land INSIDE the
        # timed window — it models a slow step, and the observability
        # plane (step-time histogram, time-series, anomaly engine) has
        # to see it exactly like a real one (a kill/exit does not care,
        # and this way the step_begin flight event precedes it)
        from horovod_tpu import chaos
        chaos.step_tick(self._steps)

    def on_step_end(self, units: Optional[float] = None) -> None:
        dt = self.timer.end_step(
            self.units_per_step if units is None else units)
        self._steps += 1
        if self.anomaly_engine is not None and dt is not None:
            # exposed-comm gauge is optional (eager overlap path only);
            # Registry.get never creates — absent stays absent
            exposed = self.timer._reg.get(
                "hvd_overlap_exposed_comm_seconds")
            thr = self.timer.throughput.value or None
            try:
                self.anomaly_engine.observe_step(
                    int(self.timer.steps.value), dt, units_per_s=thr,
                    exposed_comm_s=exposed.value
                    if exposed is not None else None)
            except Exception:
                pass  # detection must never break the loop
        if self.timer.flops_per_step is None and self._lowerable is not None:
            from horovod_tpu.metrics.mfu import hlo_flops_per_device
            try:
                jitted, fargs = self._lowerable()
                self.timer.set_flops_per_step(hlo_flops_per_device(
                    jitted, fargs, factor=self._hlo_factor))
            except Exception:
                pass
            finally:
                self._lowerable = None  # one attempt: lowering isn't free
        if self._log_every > 0 and self._steps % self._log_every == 0 \
                and dt is not None:
            get_logger().info(
                "telemetry: step %d took %.4fs (%.1f %s/s, mfu=%s)",
                self._steps, dt,
                self.timer.throughput.value, self.timer.unit,
                f"{self.timer.last_mfu:.3f}"
                if self.timer.last_mfu is not None else "n/a")

    def on_epoch_end(self, logs: Dict[str, Any]) -> Dict[str, Any]:
        """Pass-through hook so the callback can ride the same list as
        :class:`MetricAverageCallback`."""
        return logs

    def on_train_end(self, *args, **kwargs) -> None:
        """Stand down the hang watchdog: after the last step, a long
        eval/export phase with no step completions is legitimate, not a
        hang (the watchdog is suspended, not dropped — a later
        ``hvd.init`` or ``ensure_watchdog`` re-arms it)."""
        from horovod_tpu.diagnostics import watchdog as _wd
        _wd.suspend()


class CheckpointCallback:
    """Durable periodic checkpointing through the native sharded store
    (:class:`horovod_tpu.checkpoint.ShardedCheckpointer`; docs/ELASTIC.md
    "Durable commits").  Every rank must run the callback — each writes
    only its shard of the state.

    Hooks follow this module's convention::

        ckpt = CheckpointCallback("/ckpt/run1", every_n_steps=200)
        state = ckpt.on_train_begin(state)      # resume if possible
        for step in range(ckpt.next_step, total_steps):
            state = train_step(state, batch)
            ckpt.on_step_end(step, state)       # async save every N
        ckpt.on_train_end(step, state)          # final synchronous save

    Saves are asynchronous (device→host snapshot inline, disk on the
    store's writer thread); save/restore bytes + durations land on
    ``/metrics``.  ``directory`` defaults to the ``CHECKPOINT_DIR`` env
    knob (docs/KNOBS.md).
    """

    def __init__(self, directory: Optional[str] = None,
                 every_n_steps: int = 100,
                 max_to_keep: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 store=None) -> None:
        if store is None:
            from horovod_tpu.checkpoint import ShardedCheckpointer
            from horovod_tpu.common.config import env_str
            directory = directory or env_str("CHECKPOINT_DIR")
            if not directory:
                raise ValueError(
                    "CheckpointCallback needs a directory (argument or "
                    "the CHECKPOINT_DIR / HVD_TPU_CHECKPOINT_DIR env "
                    "knob)")
            store = ShardedCheckpointer(directory, max_to_keep=max_to_keep,
                                        max_inflight=max_inflight)
        self.store = store
        self.every_n_steps = int(every_n_steps)
        self.restored_step: Optional[int] = None
        self._last_saved = -1

    @property
    def next_step(self) -> int:
        """First step the loop should run: 0 on a fresh start,
        ``restored_step + 1`` after a restore (restored_step can BE 0 —
        don't use ``restored_step or -1``, 0 is falsy)."""
        return 0 if self.restored_step is None else self.restored_step + 1

    def on_train_begin(self, state):
        """Restore the latest checkpoint onto the CURRENT mesh (``state``
        is the ``like=`` template) or return ``state`` untouched."""
        out = self.store.restore_latest(like=state)
        if out is None:
            return state
        self.restored_step = self.store.latest_step()
        self._last_saved = self.restored_step
        return out

    def on_step_end(self, step: int, state) -> None:
        if self.every_n_steps > 0 and step > self._last_saved \
                and step % self.every_n_steps == 0:
            self.store.save(step, state)
            self._last_saved = step

    def on_epoch_end(self, logs: Dict[str, Any]) -> Dict[str, Any]:
        """Pass-through so the callback rides the same list as
        :class:`MetricAverageCallback`."""
        return logs

    def on_train_end(self, step: Optional[int] = None,
                     state: Any = None) -> None:
        """Final synchronous save (when ``step``/``state`` are given and
        newer than the last save), then drain the writer."""
        if state is not None and step is not None \
                and step > self._last_saved:
            self.store.save(step, state)
            self._last_saved = step
        self.store.wait()

    def close(self) -> None:
        self.store.close()


class LearningRateWarmupCallback:
    """Linear LR warmup from ``initial_lr/size`` to ``initial_lr * size``
    over warmup epochs (reference: ``LearningRateWarmupCallbackImpl:118-192``
    — the "facebook 1-hour" scaling recipe). Returns a schedule fn usable as
    an optax learning-rate schedule."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 steps_per_epoch: int = 1, momentum_correction: bool = True,
                 verbose: bool = False) -> None:
        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose

    def schedule(self) -> Callable[[int], float]:
        import jax.numpy as jnp
        scale = size()
        warm_steps = max(1, self.warmup_epochs * self.steps_per_epoch)
        base = self.initial_lr

        def fn(step):
            frac = jnp.minimum(step / warm_steps, 1.0)
            # exponential ramp from lr to lr*size (reference uses
            # lr * (size ** (epoch/warmup)) per batch)
            return base * (scale ** frac)

        return fn
