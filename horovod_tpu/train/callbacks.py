"""Training-loop callbacks.

Reference: ``horovod/_keras/callbacks.py`` — ``BroadcastGlobalVariables``
(:23-47), ``MetricAverageCallback`` (:49-93), ``LearningRateWarmupCallback``
(:118-192). The reference hooks Keras; here the hooks are framework-neutral
callables for JAX training loops (works with any loop that calls
``on_train_begin`` / ``on_epoch_end``-style hooks or uses them directly).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from horovod_tpu.common.basics import rank, size
from horovod_tpu.ops import collectives as C
from horovod_tpu.ops.reduce_op import Average


class BroadcastGlobalVariablesCallback:
    """Broadcast params/opt-state from root at training start (reference:
    ``BroadcastGlobalVariablesCallbackImpl:23-47``)."""

    def __init__(self, root_rank: int = 0) -> None:
        self.root_rank = root_rank

    def on_train_begin(self, params, opt_state=None):
        from horovod_tpu.train.optimizer import (broadcast_optimizer_state,
                                                 broadcast_parameters)
        params = broadcast_parameters(params, self.root_rank)
        if opt_state is not None:
            opt_state = broadcast_optimizer_state(opt_state, self.root_rank)
            return params, opt_state
        return params


class MetricAverageCallback:
    """Average logged metrics across workers at epoch end (reference:
    ``MetricAverageCallbackImpl:49-93``)."""

    def on_epoch_end(self, logs: Dict[str, Any]) -> Dict[str, Any]:
        if size() == 1:
            return dict(logs)
        out = {}
        for k, v in logs.items():
            if isinstance(v, (int, float, np.floating, np.integer)):
                red = C.allreduce(np.asarray([float(v)], np.float64),
                                  op=Average, name=f"metric.{k}")
                out[k] = float(np.asarray(red)[0])
            else:
                out[k] = v
        return out


class LearningRateWarmupCallback:
    """Linear LR warmup from ``initial_lr/size`` to ``initial_lr * size``
    over warmup epochs (reference: ``LearningRateWarmupCallbackImpl:118-192``
    — the "facebook 1-hour" scaling recipe). Returns a schedule fn usable as
    an optax learning-rate schedule."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 steps_per_epoch: int = 1, momentum_correction: bool = True,
                 verbose: bool = False) -> None:
        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose

    def schedule(self) -> Callable[[int], float]:
        import jax.numpy as jnp
        scale = size()
        warm_steps = max(1, self.warmup_epochs * self.steps_per_epoch)
        base = self.initial_lr

        def fn(step):
            frac = jnp.minimum(step / warm_steps, 1.0)
            # exponential ramp from lr to lr*size (reference uses
            # lr * (size ** (epoch/warmup)) per batch)
            return base * (scale ** frac)

        return fn
