"""Distributed training API: optimizer wrapper, gradient transform, parameter
broadcast.

TPU-native re-think of the reference's high-level API:

* reference ``_DistributedOptimizer`` hooks torch grad accumulators
  (``horovod/torch/optimizer.py:128-171``) and allreduces each grad
  asynchronously; here the same contract is an **optax gradient
  transformation** — the JAX-idiomatic seam for "do something to gradients
  before the update".
* reference ``DistributedGradientTape`` (``horovod/tensorflow/__init__.py:777``)
  wraps ``tape.gradient``; here :func:`distributed_grad` wraps
  ``jax.value_and_grad``.
* reference ``broadcast_parameters`` / ``broadcast_optimizer_state`` /
  ``broadcast_object`` (``horovod/torch/functions.py:29-266``) map to pytree
  broadcasts.

Execution regimes of the gradient sync (``DistributedGradTransform``):

* **global-SPMD jit** (one program over a global mesh, batch sharded):
  XLA inserts the reduction from shardings — the transform is an identity
  (modulo pre/post-scale). This is the default traced behavior.
* **shard_map** with a live ``axis_name``: explicit in-graph ``psum/pmean``.
* **eager multi-process**: grouped host allreduce through the backend
  (the C++ core fuses the whole set into large buffers, as the reference's
  fusion buffer does — ``fusion_buffer_manager.h:30-56``).
* **per-process jit + host sync** (``host_sync_in_jit=True``): an ordered
  ``io_callback`` hands gradients to the negotiating host core from inside
  the compiled step — for programs jitted per process over LOCAL arrays
  only. Requires the TCP core backend (device-data-plane backends would
  re-enter the device from the callback).
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, NamedTuple, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.common.basics import _require_init, rank, size
from horovod_tpu.common.process_sets import ProcessSet, global_process_set
from horovod_tpu.common.util import is_traced as _is_traced
from horovod_tpu.compression import (Compression, Compressor, EFState,
                                     ErrorFeedback, Quantizer, ef_apply,
                                     init_residual)
from horovod_tpu.ops import collectives as C
from horovod_tpu.ops.reduce_op import Average, ReduceOp, Sum


def _record_sync_timing(exposed_s: float, total_s: float,
                        n_buckets: int) -> None:
    """Overlap efficiency on /metrics (docs/OBSERVABILITY.md): how much
    of the eager gradient sync was spent BLOCKED on the wire (exposed)
    vs overlapped with local codec/enqueue work."""
    from horovod_tpu.metrics.registry import default_registry
    reg = default_registry()
    reg.gauge("hvd_overlap_exposed_comm_seconds",
              help="seconds blocked on collective completion in the last "
              "gradient sync").set(exposed_s)
    reg.gauge("hvd_overlap_sync_seconds",
              help="wall seconds of the last eager gradient sync"
              ).set(total_s)
    reg.counter("hvd_overlap_exposed_comm_seconds_total",
                help="cumulative exposed-communication seconds"
                ).inc(exposed_s)
    reg.gauge("hvd_overlap_bucket_count",
              help="gradient buckets in the active overlap plan"
              ).set(n_buckets)


def _eager_allreduce_tree(grads, op: ReduceOp, process_set: ProcessSet,
                          compression: Compressor,
                          prescale: float, postscale: float,
                          bucket_bytes=None):
    """Bucketed (fused) eager allreduce of a gradient pytree.

    The tree is partitioned into byte-budgeted buckets in reverse
    registration order (``train/buckets.py``, the engine's
    fusion-threshold budget) and each bucket is issued as ONE async
    group: bucket ``b``'s payload is on the wire while bucket ``b+1``
    is still being compressed/enqueued — the eager-path analog of the
    reference's background thread reducing early gradients mid-backward.
    ``HVD_TPU_OVERLAP_BUCKETS=0`` restores the single grouped call.

    Cast compressors ride the plain grouped allreduce in their wire
    dtype (sum in fp16/bf16 is well-defined); quantizers take the
    quantized allgather path (``C.quantized_grouped_allreduce``) — their
    per-block-scaled payloads are not sum-reducible, and the C++ wire
    moves ~4x fewer bytes for the int8 codec. Exposed-communication
    seconds (time blocked in ``wait`` after all local work) land on the
    overlap metrics either way."""
    import time as _time

    from horovod_tpu.common.config import get_config
    from horovod_tpu.train.buckets import Bucket, BucketPlan, plan_buckets

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    if get_config().overlap_buckets and len(leaves) > 1:
        plan = plan_buckets(leaves, bucket_bytes)
    else:
        from horovod_tpu.train.buckets import _leaf_nbytes
        nbytes = sum(_leaf_nbytes(l) for l in leaves)
        plan = BucketPlan((Bucket(tuple(range(len(leaves))), nbytes),),
                          nbytes)

    quantized = isinstance(compression, Quantizer)
    t0 = _time.perf_counter()
    pending = []  # (bucket, handle, ctxs or None)
    for bi, bucket in enumerate(plan.buckets):
        vals = [leaves[i] for i in bucket.indices]
        if quantized:
            if prescale != 1.0:
                vals = [v * prescale for v in vals]
            h = C.quantized_grouped_allreduce_async(
                vals, compression, op=op, name=f"grad.b{bi}",
                process_set=process_set)
            pending.append((bucket, h, None))
        else:
            compressed, ctxs = [], []
            for leaf in vals:
                c, ctx = compression.compress(leaf)
                compressed.append(c)
                ctxs.append(ctx)
            h = C.grouped_allreduce_async(
                compressed, op=op, name=f"grad.b{bi}",
                prescale_factor=prescale, postscale_factor=postscale,
                process_set=process_set)
            pending.append((bucket, h, ctxs))

    out: list = [None] * len(leaves)
    exposed = 0.0
    for bucket, h, ctxs in pending:
        tw = _time.perf_counter()
        reduced = h.wait()
        exposed += _time.perf_counter() - tw
        if ctxs is None:
            if postscale != 1.0:
                reduced = [r * postscale for r in reduced]
        else:
            reduced = [compression.decompress(r, ctx)
                       for r, ctx in zip(reduced, ctxs)]
        for i, r in zip(bucket.indices, reduced):
            out[i] = r
    _record_sync_timing(exposed, _time.perf_counter() - t0,
                        plan.num_buckets)
    return jax.tree_util.tree_unflatten(treedef, out)


_warned_traced_identity = False


def _warn_traced_identity_once() -> None:
    """The traced no-axis path is an identity, which is only correct under
    single-program global-SPMD jit. A reference user who jits a PER-PROCESS
    train step with size() > 1 would get silently divergent replicas — too
    dangerous to leave undetected on a drop-in surface (ADVICE r1)."""
    global _warned_traced_identity
    if _warned_traced_identity:
        return
    _warned_traced_identity = True
    import warnings
    warnings.warn(
        "horovod_tpu: gradient sync was traced with size() > 1 but no "
        "axis_name and host_sync_in_jit=False. This is an IDENTITY: it is "
        "correct only when the step is jitted once over a GLOBAL mesh "
        "(global-SPMD, XLA reduces from shardings). If you are jitting a "
        "per-process step over local arrays (the reference pattern), your "
        "replicas will silently diverge — pass axis_name= under shard_map, "
        "or host_sync_in_jit=True with the TCP core backend. See the "
        "'Execution regimes' section of horovod_tpu.train.optimizer.",
        UserWarning, stacklevel=4)


def _traced_allreduce_tree(grads, op: ReduceOp, axis_name: Optional[str],
                           prescale: float, postscale: float):
    """Inside jit/shard_map: emit in-graph collectives.

    With no live named axis (plain global-SPMD jit), gradients are already
    globally reduced by XLA from the shardings, so this is an identity modulo
    pre/post-scale. With a named axis (shard_map per-device training loops),
    emit the explicit in-graph collective — the XLA analog of the NCCL launch
    in ``nccl_operations.cc:156-214``.
    """
    from horovod_tpu.ops.mesh_collectives import preduce

    if axis_name is None and size() > 1:
        _warn_traced_identity_once()

    def one(g):
        if prescale != 1.0:
            g = g * prescale
        if axis_name is not None:
            g = preduce(g, axis_name, op)
        if postscale != 1.0:
            g = g * postscale
        return g
    return jax.tree_util.tree_map(one, grads)


class DistributedState(NamedTuple):
    inner_state: Any


def _host_callback_allreduce_tree(grads, op: ReduceOp,
                                  process_set: ProcessSet,
                                  compression: Compressor,
                                  prescale: float, postscale: float):
    """Cross-process sync from INSIDE jit (SURVEY.md §7 hard part (d)):
    an ordered ``io_callback`` hands the gradient tree to the host backend
    mid-program. jit traces once, so every process emits the identical
    callback sequence — exactly the same-order contract the eager path
    already relies on — and the C++ core negotiates/fuses as usual.

    Only valid for PER-PROCESS jit over local arrays with the host (TCP
    core) backend: under global-SPMD, GSPMD pins callbacks to device 0's
    process (the others would never call in → deadlock), and device-data-
    plane backends (XLA_EAGER) would re-enter the devices that are blocked
    on this very callback.
    """
    from jax.experimental import io_callback

    be = _require_init().backend
    from horovod_tpu.core.core_backend import CoreBackend
    if not isinstance(be, CoreBackend):
        raise RuntimeError(
            "host_sync_in_jit requires the TCP core backend; the "
            f"{type(be).__name__} data plane cannot be driven from inside "
            "a compiled program (unset HOROVOD_TPU_OPERATIONS, or use "
            "global-SPMD sharding / an explicit axis_name instead)")

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    shapes = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]

    def host(*flat):
        tree = jax.tree_util.tree_unflatten(treedef, list(flat))
        out = _eager_allreduce_tree(tree, op, process_set, compression,
                                    prescale, postscale)
        return tuple(np.asarray(x) for x in
                     jax.tree_util.tree_leaves(out))

    out_flat = io_callback(host, tuple(shapes), *leaves, ordered=True)
    return jax.tree_util.tree_unflatten(treedef, list(out_flat))


def DistributedGradTransform(op: ReduceOp = Average,
                             process_set: ProcessSet = global_process_set,
                             compression: Compressor = Compression.none,
                             axis_name: Optional[str] = None,
                             prescale_factor: float = 1.0,
                             postscale_factor: float = 1.0,
                             host_sync_in_jit: bool = False,
                             bucket_bytes: Optional[int] = None
                             ) -> optax.GradientTransformation:
    """optax transform that synchronizes gradients across the process set.

    The moral equivalent of the reference's per-parameter allreduce hooks
    (``torch/optimizer.py:164-206``), but batched over the whole tree so the
    core can fuse one buffer per cycle instead of negotiating per-tensor.

    Regimes (see module docstring): eager multi-process → grouped host
    allreduce; ``axis_name`` under shard_map → in-graph collective;
    traced with no axis → identity by default (global-SPMD jit: XLA
    reduces from shardings), or — with ``host_sync_in_jit=True`` and a
    per-process jit over local arrays — an ordered ``io_callback`` into
    the negotiating core.

    ``compression`` accepts the cast compressors (fp16/bf16 wire
    dtype), a quantizer (``Compression.int8``/``fp8``/``onebit`` — the
    eager wire then moves quantized payloads), or
    ``ErrorFeedback(codec)``: the transform state grows a per-leaf fp32
    residual and every step compresses ``grad + residual``, carrying
    the quantization error to the next step (so lossy codecs converge —
    docs/PERF.md "Gradient compression"). With EF the in-graph
    quantize∘dequantize runs in EVERY regime, including global-SPMD jit
    where the sync itself is an identity; a bare (non-EF) quantizer
    compresses the eager wire only — traced regimes leave gradients to
    XLA's sharding-derived reduction untouched.
    """
    ef = isinstance(compression, ErrorFeedback)
    codec = compression.inner if ef else compression

    def init_fn(params):
        if ef:
            return EFState(residual=init_residual(params))
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        if ef:
            # compress(grad + residual), carry the error; the synced
            # values are the (losslessly re-quantizable) compressed ones
            updates, new_residual = ef_apply(codec, updates, state.residual)
        if _is_traced(updates):
            if host_sync_in_jit and axis_name is None and size() > 1:
                new = _host_callback_allreduce_tree(
                    updates, op, process_set, codec,
                    prescale_factor, postscale_factor)
            else:
                new = _traced_allreduce_tree(updates, op, axis_name,
                                             prescale_factor,
                                             postscale_factor)
        elif size() == 1:
            new = _traced_allreduce_tree(updates, op, None,
                                         prescale_factor, postscale_factor)
        else:
            new = _eager_allreduce_tree(updates, op, process_set, codec,
                                        prescale_factor, postscale_factor,
                                        bucket_bytes)
        return new, (EFState(residual=new_residual) if ef else state)

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         op: ReduceOp = Average,
                         process_set: ProcessSet = global_process_set,
                         compression: Compressor = Compression.none,
                         backward_passes_per_step: int = 1,
                         axis_name: Optional[str] = None,
                         prescale_factor: float = 1.0,
                         postscale_factor: float = 1.0,
                         host_sync_in_jit: bool = False,
                         autotune=None
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer with distributed gradient synchronization.

    Reference: ``hvd.DistributedOptimizer`` factory
    (``horovod/torch/optimizer.py:506``, ``horovod/tensorflow/__init__.py:627``).
    ``backward_passes_per_step > 1`` reproduces the reference's delayed
    allreduce (local accumulation, sync every k steps —
    ``torch/optimizer.py:249-292``) via ``optax.MultiSteps``.
    ``compression`` accepts casts, quantizers, or ``ErrorFeedback(...)``
    (see :func:`DistributedGradTransform`); the Adasum path has no
    compression seam — combining them raises.

    ``autotune=True`` warm-starts the communication knobs from the
    persistent plan cache (docs/PERF.md "Autotuning"): at ``init`` the
    gradient tree's fingerprint is looked up in
    ``HVD_TPU_AUTOTUNE_CACHE_DIR`` and a hit applies the tuned
    ``bucket_bytes`` (and — when you passed no ``compression`` of your
    own — the tuned codec, wrapped in error feedback so the lossy wire
    converges). A miss keeps your settings unchanged: the ONLINE search
    that fills the cache lives in
    ``make_overlap_train_step(..., autotune=True)``, because restarting
    the search per candidate means recompiling the step — something an
    optax transform cannot do from inside your jit.
    """
    from horovod_tpu.train.fused_apply import (FusedOptSpec,
                                               make_fused_transform)
    env_autotune = False
    if autotune is None:
        from horovod_tpu.common.config import get_config
        autotune = get_config().autotune_mesh
        env_autotune = bool(autotune)
    if autotune:
        if op == ReduceOp.ADASUM or isinstance(optimizer, FusedOptSpec):
            if not env_autotune:
                raise ValueError(
                    "autotune= applies to the standard sync path only "
                    "(Adasum has no codec/bucket seam; the fused apply "
                    "pins its own codec)")
            autotune = False  # fleet-wide env default: skip, don't raise
    if autotune:
        return _warm_start_optimizer(
            optimizer, op=op, process_set=process_set,
            compression=compression,
            backward_passes_per_step=backward_passes_per_step,
            axis_name=axis_name, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            host_sync_in_jit=host_sync_in_jit)
    if isinstance(optimizer, FusedOptSpec):
        # fused dequantize+apply path (train/fused_apply.py): sync and
        # optimizer lower into ONE transform so the int8 codes feed the
        # Pallas kernel directly — no separate dequantize sweep.
        if op == ReduceOp.ADASUM:
            raise ValueError("fused_sgd/fused_adam have no Adasum path")
        if prescale_factor != 1.0 or postscale_factor != 1.0:
            raise ValueError(
                "fused apply does not take pre/postscale factors; fold "
                "them into the learning rate")
        if host_sync_in_jit:
            raise ValueError(
                "fused apply and host_sync_in_jit are mutually "
                "exclusive (the fused path keeps codes on device)")
        fused = make_fused_transform(optimizer, op=op,
                                     process_set=process_set,
                                     compression=compression,
                                     axis_name=axis_name)
        if backward_passes_per_step > 1:
            return optax.MultiSteps(
                fused, every_k_schedule=backward_passes_per_step)
        return fused
    if op == ReduceOp.ADASUM:
        if compression is not Compression.none:
            raise ValueError(
                "op=Adasum has no compression seam (the scaled-add tree "
                "needs exact contributions); drop compression= or use a "
                "different op")
        from horovod_tpu.ops.adasum import AdasumGradTransform
        sync = AdasumGradTransform(process_set=process_set,
                                   axis_name=axis_name)
    else:
        sync = DistributedGradTransform(op, process_set, compression,
                                        axis_name, prescale_factor,
                                        postscale_factor, host_sync_in_jit)
    chained = optax.chain(sync, optimizer)
    if backward_passes_per_step > 1:
        return optax.MultiSteps(chained,
                                every_k_schedule=backward_passes_per_step)
    return chained


def _warm_start_optimizer(optimizer, *, op, process_set, compression,
                          backward_passes_per_step, axis_name,
                          prescale_factor, postscale_factor,
                          host_sync_in_jit) -> optax.GradientTransformation:
    """``DistributedOptimizer(autotune=True)``: resolve the tuned plan
    lazily at ``init`` — the first moment the gradient-tree structure
    (== params structure) is in hand to fingerprint — then build the
    real sync chain with the cached ``bucket_bytes``/codec applied.
    A cache miss (or no cache dir) degrades to the caller's settings
    unchanged; resolution NEVER raises."""
    cell: dict = {}

    def _build(params):
        from horovod_tpu.common.topology import detect_topology
        from horovod_tpu.train.autotune import (PlanCache,
                                                plan_fingerprint,
                                                resolve_cache_dir,
                                                topology_key)
        comp, bucket = compression, None
        try:
            cache_dir = resolve_cache_dir(None)
            if cache_dir:
                # canonical topology key (NOT a mesh-axis-name dict):
                # hits entries the mesh search wrote for the same model
                # at this world size regardless of what the axis was
                # called over there. Prefer the launcher's own
                # hosts×local split (the eager world has no mesh to
                # inspect); virtual-hosts/flat fallback otherwise.
                from horovod_tpu.common.basics import local_size
                from horovod_tpu.common.topology import MeshTopology
                w, ls = size(), local_size()
                if ls > 0 and w % ls == 0 and w // ls > 1:
                    topo = MeshTopology(w // ls, ls)
                else:
                    topo = detect_topology(n=w)
                fp = plan_fingerprint(params, topology_key(topo), w)
                plan = PlanCache(cache_dir).load(fp)
                if plan is not None:
                    bucket = plan.bucket_bytes
                    codec = plan.resolve_codec()
                    if codec is not None and \
                            compression is Compression.none:
                        # lossy codec on the wire needs the residual
                        # carry to converge (docs/PERF.md)
                        comp = ErrorFeedback(codec)
                    from horovod_tpu.diagnostics.flight_recorder import \
                        record_event
                    record_event("autotune_warm_start", plan=plan.key)
                    from horovod_tpu.metrics.registry import \
                        default_registry
                    default_registry().counter(
                        "hvd_autotune_cache_hits_total",
                        help="runs that started from a cached tuned "
                             "plan with zero search trials").inc()
        except Exception:  # warm start is best-effort, never fatal
            comp, bucket = compression, None
        sync = DistributedGradTransform(op, process_set, comp, axis_name,
                                        prescale_factor, postscale_factor,
                                        host_sync_in_jit, bucket)
        inner = optax.chain(sync, optimizer)
        if backward_passes_per_step > 1:
            inner = optax.MultiSteps(
                inner, every_k_schedule=backward_passes_per_step)
        return inner

    def init_fn(params):
        cell["inner"] = _build(params)
        return cell["inner"].init(params)

    def update_fn(updates, state, params=None):
        if "inner" not in cell:  # init skipped (restored state)
            cell["inner"] = _build(updates)
        return cell["inner"].update(updates, state, params)

    return optax.GradientTransformation(init_fn, update_fn)


def distributed_grad(fun: Callable, argnums=0, has_aux: bool = False,
                     op: ReduceOp = Average,
                     process_set: ProcessSet = global_process_set,
                     compression: Compressor = Compression.none,
                     axis_name: Optional[str] = None,
                     host_sync_in_jit: bool = False) -> Callable:
    """``jax.grad`` with cross-worker gradient reduction — the JAX analog of
    ``DistributedGradientTape`` (``horovod/tensorflow/__init__.py:777-851``).
    Same regime routing as :func:`DistributedGradTransform`; error
    feedback needs cross-step state, which a stateless grad wrapper
    cannot hold — use ``DistributedOptimizer(compression=ErrorFeedback(
    ...))`` for that."""
    if isinstance(compression, ErrorFeedback):
        raise ValueError(
            "distributed_grad is stateless and cannot carry ErrorFeedback "
            "residuals; wrap your optimizer with DistributedOptimizer("
            "compression=ErrorFeedback(...)) instead")
    vg = jax.value_and_grad(fun, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        value, grads = vg(*args, **kwargs)
        if _is_traced(grads):
            if host_sync_in_jit and axis_name is None and size() > 1:
                grads = _host_callback_allreduce_tree(
                    grads, op, process_set, compression, 1.0, 1.0)
            else:
                grads = _traced_allreduce_tree(grads, op, axis_name, 1.0,
                                               1.0)
        elif size() > 1:
            grads = _eager_allreduce_tree(grads, op, process_set, compression,
                                          1.0, 1.0)
        return value, grads

    return wrapped


# ---------------------------------------------------------------------------
# Parameter / state broadcast (reference: horovod/torch/functions.py:29-266)
# ---------------------------------------------------------------------------

def broadcast_parameters(params, root_rank: int = 0,
                         process_set: ProcessSet = global_process_set):
    """Broadcast a parameter pytree from ``root_rank`` to all workers
    (reference: ``broadcast_parameters``, ``torch/functions.py:29-68``)."""
    if size() == 1:
        return params
    leaves, treedef = jax.tree_util.tree_flatten(params)
    # Enqueue all broadcasts before waiting so the core can fuse them into
    # few large buffers (mirrors the reference enqueuing every parameter in
    # one pass, ``torch/functions.py:58-66``).
    handles = [C.broadcast_async(leaf, root_rank, name=f"bcast.param.{i}",
                                 process_set=process_set)
               for i, leaf in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef,
                                        [h.wait() for h in handles])


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              process_set: ProcessSet = global_process_set):
    """Reference: ``broadcast_optimizer_state`` (``torch/functions.py:116-266``).
    optax states are pytrees, so this is the same tree broadcast; non-array
    leaves (step counters etc.) travel via :func:`broadcast_object`."""
    if size() == 1:
        return opt_state
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    # Async-enqueue all array broadcasts first (see broadcast_parameters);
    # non-array leaves go through the pickle path synchronously.
    handles = {}
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, (jax.Array, np.ndarray)):
            handles[i] = C.broadcast_async(leaf, root_rank,
                                           name=f"bcast.opt.{i}",
                                           process_set=process_set)
    out = []
    for i, leaf in enumerate(leaves):
        if i in handles:
            out.append(handles[i].wait())
        else:
            out.append(broadcast_object(leaf, root_rank,
                                        name=f"bcast.opt.obj.{i}",
                                        process_set=process_set))
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None,
                     process_set: ProcessSet = global_process_set):
    """Pickle-based arbitrary-object broadcast (reference:
    ``broadcast_object``, ``torch/functions.py:193-241``: serialize, bcast
    length, bcast bytes)."""
    if size() == 1:
        return obj
    name = name or "broadcast_object"
    if rank() == root_rank:
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()
        length = np.array([payload.size], dtype=np.int64)
    else:
        payload = None
        length = np.zeros(1, dtype=np.int64)
    length = np.asarray(C.broadcast(length, root_rank, name=f"{name}.len",
                                    process_set=process_set))
    if rank() != root_rank:
        payload = np.zeros(int(length[0]), dtype=np.uint8)
    payload = np.asarray(C.broadcast(payload, root_rank, name=f"{name}.data",
                                     process_set=process_set))
    return pickle.loads(payload.tobytes())


def allgather_object(obj, name: Optional[str] = None,
                     process_set: ProcessSet = global_process_set):
    """Pickle-based arbitrary-object allgather: returns the list of every
    rank's object, ordered by rank (reference: ``allgather_object``,
    ``torch/functions.py:233-266``: serialize, allgather sizes, allgather
    ragged bytes, split)."""
    if size() == 1:
        return [obj]
    name = name or "allgather_object"
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()
    # enqueue both collectives before waiting (independent: the backend
    # handles ragged dim 0 itself) so the core can fuse them in one
    # negotiation cycle, as broadcast_parameters does
    sizes_h = C.allgather_async(np.array([payload.size], dtype=np.int64),
                                name=f"{name}.len", process_set=process_set)
    data_h = C.allgather_async(payload, name=f"{name}.data",
                               process_set=process_set)
    sizes = np.asarray(sizes_h.wait())
    gathered = np.asarray(data_h.wait())
    out, offset = [], 0
    for n in sizes.tolist():
        out.append(pickle.loads(gathered[offset:offset + n].tobytes()))
        offset += n
    return out
