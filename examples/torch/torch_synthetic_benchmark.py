"""Synthetic throughput harness for the torch drop-in adapter.

Reference analog: ``examples/pytorch/pytorch_synthetic_benchmark.py`` —
the canonical "always prints img/sec" harness: warm-up batches, timed
iterations, per-rank rate allreduced to a total. The reference benches
torchvision models on GPU; here the adapter is host-side (the TPU compute
path is JAX — see ``bench.py`` for the chip benchmarks), so the default
model is a small conv net and the number this prints measures the
adapter + TCP-core data plane, not an accelerator.

Run:
    python examples/torch/torch_synthetic_benchmark.py
    hvdrun -np 2 python examples/torch/torch_synthetic_benchmark.py \
        --fp16-allreduce
"""

import argparse
import timeit

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


def small_conv(classes=10):
    return nn.Sequential(
        nn.Conv2d(3, 32, 3, padding=1), nn.ReLU(),
        nn.Conv2d(32, 64, 3, stride=2, padding=1), nn.ReLU(),
        nn.AdaptiveAvgPool2d(1), nn.Flatten(), nn.Linear(64, classes))


def main():
    p = argparse.ArgumentParser(
        description="Torch adapter synthetic benchmark",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="fp16 compression during allreduce")
    p.add_argument("--use-adasum", action="store_true",
                   help="adasum reduction instead of averaging")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--num-warmup-batches", type=int, default=5)
    p.add_argument("--num-batches-per-iter", type=int, default=5)
    p.add_argument("--num-iters", type=int, default=5)
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(0)

    model = small_conv()
    lr_scaler = hvd.size() if not args.use_adasum else 1
    opt = torch.optim.SGD(model.parameters(), lr=0.01 * lr_scaler)
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(),
        compression=compression,
        op=hvd.Adasum if args.use_adasum else hvd.Average)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    data = torch.randn(args.batch_size, 3, args.image_size, args.image_size)
    target = torch.randint(0, 10, (args.batch_size,))

    def benchmark_step():
        opt.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        opt.step()

    def log(s):
        if hvd.rank() == 0:
            print(s)

    log(f"Model: small_conv, batch size {args.batch_size}, "
        f"{hvd.size()} process(es)")
    timeit.timeit(benchmark_step, number=args.num_warmup_batches)

    img_secs = []
    for _ in range(args.num_iters):
        t = timeit.timeit(benchmark_step, number=args.num_batches_per_iter)
        img_secs.append(args.batch_size * args.num_batches_per_iter / t)

    img_sec_mean, img_sec_conf = np.mean(img_secs), 1.96 * np.std(img_secs)
    log(f"Img/sec per process: {img_sec_mean:.1f} +- {img_sec_conf:.1f}")
    total = hvd.allreduce(torch.tensor([img_sec_mean]), op=hvd.Sum,
                          name="total_img_sec")
    log(f"Total img/sec on {hvd.size()} process(es): "
        f"{float(total[0]):.1f} +- {hvd.size() * img_sec_conf:.1f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
