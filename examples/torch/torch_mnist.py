"""Drop-in PyTorch training with the torch adapter.

Run single-process:          python examples/torch/torch_mnist.py
Run multi-process (2 ranks): hvdrun -np 2 python examples/torch/torch_mnist.py

Reference analog: ``examples/pytorch/pytorch_mnist.py`` — a reference user
changes ``import horovod.torch as hvd`` to ``import horovod_tpu.torch as
hvd`` and keeps the rest of the script: DistributedOptimizer with gradient
hooks, broadcast of parameters and optimizer state from rank 0, per-rank
data shard, metric allreduce. Synthetic data keeps it hermetic.
"""

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(64, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        return self.fc2(torch.tanh(self.fc1(x)))


def make_data(n=4096, d=64, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes)
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ w + 0.1 * rng.randn(n, classes)).argmax(-1)
    return torch.from_numpy(x), torch.from_numpy(y)


def main():
    hvd.init()
    torch.manual_seed(42)

    x, y = make_data()
    # per-rank shard (reference: DistributedSampler)
    shard = slice(hvd.rank(), None, hvd.size())
    x, y = x[shard], y[shard]

    model = Net()
    opt = torch.optim.SGD(model.parameters(), lr=0.05, momentum=0.9)

    # identical start everywhere, then hook-driven gradient averaging
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(),
        compression=hvd.Compression.fp16)

    batch = 128
    for epoch in range(3):
        perm = torch.randperm(len(x))
        loss = torch.zeros(())  # shard smaller than one batch: no steps
        for i in range(0, len(x) - batch + 1, batch):
            idx = perm[i:i + batch]
            opt.zero_grad()
            loss = F.cross_entropy(model(x[idx]), y[idx])
            loss.backward()
            opt.step()
        avg = hvd.allreduce(loss.detach(), name="epoch_loss")
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {float(avg):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
