"""Elastic PyTorch training: TorchState + ElasticSampler.

Run with a changing world:
    hvdrun -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./discover.sh \
        python examples/torch/torch_elastic_mnist.py

Reference analog: ``examples/elastic/pytorch/pytorch_mnist_elastic.py`` —
the ``@hvd.elastic.run`` decorator retries the training function across
world-size changes; ``TorchState`` commits/restores model + optimizer +
sampler; ``ElasticSampler`` re-shards unprocessed indices so no example is
dropped or repeated within an epoch after a resize.
"""

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd
import horovod_tpu.elastic as elastic
from horovod_tpu.torch.elastic import ElasticSampler, TorchState


def make_data(n=2048, d=32, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes)
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ w).argmax(-1)
    return torch.from_numpy(x), torch.from_numpy(y)


def main():
    hvd.init()
    torch.manual_seed(0)
    x, y = make_data()

    model = torch.nn.Sequential(
        torch.nn.Linear(32, 64), torch.nn.Tanh(), torch.nn.Linear(64, 10))
    opt = hvd.DistributedOptimizer(
        torch.optim.Adam(model.parameters(), lr=1e-3),
        named_parameters=model.named_parameters())
    sampler = ElasticSampler(range(len(x)), shuffle=True)
    state = TorchState(model=model, optimizer=opt, sampler=sampler,
                       epoch=0, batch_idx=0)

    @elastic.run
    def train(state):
        batch = 64
        while state.epoch < 3:
            # iterating the sampler re-derives this rank's shard of the
            # indices NOT yet processed this epoch (elastic resume point)
            order = list(sampler)
            loss = None
            for i in range(0, len(order), batch):
                idx = order[i:i + batch]
                opt.zero_grad()
                loss = F.cross_entropy(model(x[idx]), y[idx])
                loss.backward()
                opt.step()
                sampler.record_indices(idx)
                state.batch_idx += 1
                if state.batch_idx % 10 == 0:
                    state.commit()  # host updates surface here
            if hvd.rank() == 0 and loss is not None:
                print(f"epoch {state.epoch}: loss "
                      f"{float(loss.detach()):.4f} world={hvd.size()}")
            state.epoch += 1
            state.batch_idx = 0
            # contract: set_epoch at the END of the epoch clears the
            # processed set (see ElasticSampler docstring)
            sampler.set_epoch(state.epoch)
            state.commit()

    train(state)
    hvd.shutdown()


if __name__ == "__main__":
    main()
