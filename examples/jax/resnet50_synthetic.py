"""ResNet-50 synthetic-data throughput (reference analog:
``examples/pytorch/pytorch_synthetic_benchmark.py`` /
``examples/tensorflow2/tensorflow2_synthetic_benchmark.py``).

Prints img/sec like the reference's synthetic benchmarks; ``bench.py`` at
the repo root is the driver-facing single-line variant of this script.
"""

import argparse
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models.resnet import (ResNet50, batch_sharding,
                                       create_resnet_state,
                                       make_resnet_train_step)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256,
                    help="per-chip batch size")
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--num-warmup", type=int, default=3)
    args = ap.parse_args()

    hvd.init()
    mesh = hvd.build_mesh(dp=-1)
    n_chips = jax.device_count()
    B = args.batch_size * n_chips

    model = ResNet50(dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
                     else jnp.float32)
    params, stats = create_resnet_state(model, jax.random.PRNGKey(0),
                                        mesh=mesh)
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = jax.jit(tx.init)(params)
    step = make_resnet_train_step(model, tx, mesh)

    rng = np.random.RandomState(0)
    images = jax.device_put(jnp.asarray(rng.rand(B, 224, 224, 3),
                                        model.dtype), batch_sharding(mesh))
    labels = jax.device_put(jnp.asarray(rng.randint(0, 1000, (B,)),
                                        jnp.int32), batch_sharding(mesh))

    for _ in range(args.num_warmup):
        params, stats, opt_state, loss = step(params, stats, opt_state,
                                              images, labels)
    float(loss)  # drain (block_until_ready is unreliable on this platform)

    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        params, stats, opt_state, loss = step(params, stats, opt_state,
                                              images, labels)
    float(loss)
    dt = time.perf_counter() - t0
    img_sec = B * args.num_iters / dt
    if hvd.rank() == 0:
        print(f"Total img/sec: {img_sec:.1f} "
              f"({img_sec / n_chips:.1f} per chip, {n_chips} chips)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
