"""Data-parallel MLP training — the hello-world of the framework.

Run single-process:         python examples/jax/mnist_dp.py
Run multi-process (2 hosts): hvdrun -np 2 python examples/jax/mnist_dp.py

Reference analog: ``examples/pytorch/pytorch_mnist.py`` — per-rank data
shard, DistributedOptimizer, broadcast of initial state from rank 0.
Synthetic data keeps the example hermetic (no downloads).
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.data import ShardedDataset


def make_data(n=4096, d=64, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes)
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ w + 0.1 * rng.randn(n, classes)).argmax(-1).astype(np.int32)
    return x, y


def main():
    hvd.init()
    x, y = make_data()
    ds = ShardedDataset(list(zip(x, y)), rank=max(hvd.rank(), 0),
                        size=hvd.size(), seed=1)

    params = {
        "w1": jnp.asarray(np.random.RandomState(2).randn(64, 128) * 0.1),
        "b1": jnp.zeros(128),
        "w2": jnp.asarray(np.random.RandomState(3).randn(128, 10) * 0.1),
        "b2": jnp.zeros(10),
    }
    # identical start everywhere (reference: broadcast_parameters)
    params = hvd.broadcast_parameters(params, root_rank=0)

    # gradient averaging across workers + bf16 transport compression
    tx = hvd.DistributedOptimizer(optax.adam(1e-3),
                                  compression=hvd.Compression.bf16)
    opt_state = tx.init(params)

    @jax.jit
    def loss_fn(p, xb, yb):
        h = jnp.tanh(xb @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        return optax.softmax_cross_entropy(
            logits, jax.nn.one_hot(yb, 10)).mean()

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    batch = 128
    for epoch in range(3):
        ds.set_epoch(epoch)
        items = list(ds)
        for i in range(0, len(items) - batch + 1, batch):
            xb = jnp.asarray(np.stack([it[0] for it in items[i:i + batch]]))
            yb = jnp.asarray(np.stack([it[1] for it in items[i:i + batch]]))
            loss, grads = grad_fn(params, xb, yb)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        avg = hvd.allreduce(loss, name="epoch_loss")
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {float(avg):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
