"""Flagship demo: MoE transformer LM trained with all five parallelism axes
(dp / pp / ep / sp / tp) over a single device mesh.

On a TPU slice this runs as-is; on CPU try:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/jax/transformer_5d_parallel.py
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models import (TransformerConfig, init_params,
                                init_opt_state, make_train_step,
                                shard_batch, shard_params)


def main():
    hvd.init()
    n = jax.device_count()
    # pick a mesh for the available chips (all axes exercised at n >= 32)
    if n >= 32:
        mesh = hvd.build_mesh(dp=n // 16, pp=2, ep=2, sp=2, tp=2)
        n_stages = 2
    elif n >= 8:
        mesh = hvd.build_mesh(dp=n // 8, pp=2, sp=2, tp=2)
        n_stages = 2
    else:
        mesh = hvd.build_mesh(dp=-1)
        n_stages = 1
    print("mesh:", dict(mesh.shape))

    cfg = TransformerConfig(
        vocab_size=1024, d_model=128, n_heads=8, n_layers=4, d_ff=256,
        max_seq=128, n_experts=4 if mesh.shape.get("ep", 1) > 1 else 0,
        dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
        else jnp.float32,
        n_microbatches=2, remat=True)

    params = shard_params(init_params(np.random.RandomState(0), cfg,
                                      n_stages), cfg, mesh)
    tx = optax.adamw(3e-4)
    step = make_train_step(cfg, mesh, tx)
    opt_state = init_opt_state(tx, params, mesh, cfg)

    rng = np.random.RandomState(1)
    B, S = 16, 128
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    targets = jnp.asarray(np.roll(np.asarray(tokens), -1, 1), jnp.int32)
    tokens, targets = shard_batch(tokens, targets, mesh)

    for i in range(10):
        params, opt_state, loss, aux = step(params, opt_state, tokens,
                                            targets)
        print(f"step {i}: loss {float(loss):.4f} aux {float(aux):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
