"""BERT pretraining on synthetic data (reference analog: the BASELINE's
"BERT-Large pretraining (DistributedOptimizer + fp16 compression)" config).

Use --large for BERT-Large (needs TPU HBM); default is BERT-Base-shaped but
tiny for smoke-running anywhere.
"""

import argparse
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models.bert import (Bert, BertConfig, bert_large, init_bert,
                                     make_bert_train_step)


def synthetic_batch(rng, B, S, vocab):
    return {
        "input_ids": jnp.asarray(rng.randint(0, vocab, (B, S)), jnp.int32),
        "token_type_ids": jnp.zeros((B, S), jnp.int32),
        "attention_mask": jnp.ones((B, S), bool),
        "mlm_labels": jnp.asarray(rng.randint(0, vocab, (B, S)), jnp.int32),
        "mlm_mask": jnp.asarray(rng.rand(B, S) < 0.15, jnp.float32),
        "nsp_labels": jnp.asarray(rng.randint(0, 2, (B,)), jnp.int32),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--large", action="store_true")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel width")
    args = ap.parse_args()

    hvd.init()
    mesh = hvd.build_mesh(dp=-1, tp=args.tp)
    if args.large:
        cfg = bert_large()
    else:
        cfg = BertConfig(vocab_size=8192, hidden_size=256, num_layers=4,
                         num_heads=8, intermediate_size=1024,
                         dtype=jnp.bfloat16
                         if jax.default_backend() == "tpu" else jnp.float32)
    model = Bert(cfg)
    params = init_bert(model, jax.random.PRNGKey(0), args.seq_len, mesh)
    tx = optax.adamw(1e-4)
    opt_state = jax.jit(tx.init)(params)
    step = make_bert_train_step(model, tx, mesh)

    rng = np.random.RandomState(0)
    batch = synthetic_batch(rng, args.batch_size * jax.device_count(),
                            args.seq_len, cfg.vocab_size)

    params, opt_state, loss = step(params, opt_state, batch)  # compile
    float(loss)
    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, batch)
    final = float(loss)
    dt = time.perf_counter() - t0
    if hvd.rank() == 0:
        seq_sec = args.batch_size * jax.device_count() * args.steps / dt
        print(f"loss {final:.4f}; {seq_sec:.1f} sequences/sec")
    hvd.shutdown()


if __name__ == "__main__":
    main()
