"""Elastic training with committed state (reference analog:
``examples/elastic/pytorch/pytorch_mnist_elastic.py``).

Run:  hvdrun --min-np 2 --host-discovery-script ./discover.sh \
          python examples/jax/elastic_train.py
where discover.sh prints lines like "localhost:2".
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu import elastic


def main():
    hvd.init()
    params = hvd.broadcast_parameters(
        {"w": jnp.zeros((32, 4))}, root_rank=0)
    tx = hvd.DistributedOptimizer(optax.sgd(0.05))
    state = elastic.TpuState(name="elastic_example", epoch=0,
                             params=params, opt_state=tx.init(params))

    rng = np.random.RandomState(hvd.rank())
    W_true = np.random.RandomState(0).randn(32, 4).astype(np.float32)

    @elastic.run
    def train(state):
        grad_fn = jax.jit(jax.value_and_grad(
            lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2)))
        for epoch in range(state.epoch, 20):
            x = jnp.asarray(rng.randn(64, 32), jnp.float32)
            y = x @ jnp.asarray(W_true)
            loss, grads = grad_fn(state.params, x, y)
            updates, state.opt_state = tx.update(grads, state.opt_state,
                                                 state.params)
            state.params = optax.apply_updates(state.params, updates)
            state.epoch = epoch + 1
            state.commit()  # survives worker loss / membership change
            if hvd.rank() == 0:
                print(f"epoch {epoch}: loss {float(loss):.5f}", flush=True)
        return state.epoch

    final = train(state)
    print(f"rank {hvd.rank()}: finished at epoch {final}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
