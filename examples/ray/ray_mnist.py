"""Distributed training on a Ray cluster via RayExecutor.

Run (requires ray):  python examples/ray/ray_mnist.py

Reference analog: ``examples/ray/tensorflow2_mnist_ray.py`` /
``basic_ray_elastic.py`` — the executor places one worker per slot on the
Ray cluster, wires the coordinator address through Ray actors, and runs
the training function on every rank. The training function itself is the
same JAX data-parallel loop as ``examples/jax/mnist_dp.py``.
"""


def train_fn():
    import numpy as np
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd

    hvd.init()
    rng = np.random.RandomState(0)
    w_true = rng.randn(32, 10)
    x = rng.randn(2048, 32).astype(np.float32)
    y = (x @ w_true).argmax(-1).astype(np.int32)
    shard = slice(max(hvd.rank(), 0), None, hvd.size())
    x, y = x[shard], y[shard]

    params = {"w": jnp.zeros((32, 10))}
    params = hvd.broadcast_parameters(params, root_rank=0)
    tx = hvd.DistributedOptimizer(optax.adam(1e-2))
    opt_state = tx.init(params)

    import jax
    loss_fn = jax.jit(lambda p, xb, yb: optax.softmax_cross_entropy(
        xb @ p["w"], jax.nn.one_hot(yb, 10)).mean())
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    loss = None
    for step in range(100):
        loss, grads = grad_fn(params, jnp.asarray(x), jnp.asarray(y))
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
    out = float(hvd.allreduce(loss, name="loss"))
    if hvd.rank() == 0:
        print(f"final loss {out:.4f}")
    hvd.shutdown()
    return out


def main():
    from horovod_tpu.ray import RayExecutor

    executor = RayExecutor(num_workers=2, cpus_per_worker=1)
    executor.start()
    results = executor.run(train_fn)
    print(f"per-rank results: {results}")
    executor.shutdown()


if __name__ == "__main__":
    main()
