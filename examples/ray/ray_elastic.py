"""Elastic training on a Ray cluster with ``ElasticRayExecutor.run(fn)``.

Run from a Ray driver (ray required):
    python examples/ray/ray_elastic.py

Reference analog: ``horovod.ray.ElasticRayExecutor`` (``ray/elastic.py``)
— actors host the agent transport, actor loss shrinks the job, the
respawner grows it back; the training fn uses the ``hvd.elastic`` API
exactly as under ``hvdrun``. Synthetic data keeps the example hermetic.
"""

import numpy as np


def train():
    import horovod_tpu as hvd
    import horovod_tpu.elastic as elastic

    hvd.init()

    rng = np.random.RandomState(0)
    w_true = rng.randn(8, 1)
    x = rng.randn(512, 8).astype(np.float32)
    y = (x @ w_true).astype(np.float32)

    state = elastic.ObjectState(name="ray_elastic",
                                w=np.zeros((8, 1), np.float32), step=0)

    @elastic.run
    def fit(state):
        lr = 0.1
        for step in range(state.step, 200):
            shard = np.arange(hvd.rank(), len(x), hvd.size())
            xb, yb = x[shard], y[shard]
            grad = 2 * xb.T @ (xb @ state.w - yb) / len(shard)
            state.w = state.w - lr * np.asarray(
                hvd.allreduce(grad, op=hvd.Average, name="g"))
            state.step = step + 1
            if state.step % 50 == 0:
                state.commit()
        state.commit()

    fit(state)
    loss = float(np.mean((x @ state.w - y) ** 2))
    rank = hvd.rank()
    hvd.shutdown()
    return {"rank": rank, "loss": loss}


def main():
    from horovod_tpu.ray import ElasticRayExecutor

    ex = ElasticRayExecutor(min_np=1, max_np=4)
    ex.start()
    results = ex.run(train)
    print("per-rank results:", results)
    assert all(r["loss"] < 1e-3 for r in results)


if __name__ == "__main__":
    main()
