"""Elastic TF2 training: TensorFlowKerasState + @hvd.elastic.run.

Run with a changing world:
    hvdrun -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./discover.sh \
        python examples/tensorflow2/tf2_mnist_elastic.py

Reference analog: ``examples/elastic/tensorflow2/tensorflow2_mnist_elastic.py``
— the ``@hvd.elastic.run`` decorator retries the training function across
world-size changes; ``TensorFlowKerasState`` snapshots model + optimizer
variables together with scalar progress counters, restores them after a
failed commit window, and re-broadcasts from the coordinator after each
resize. Synthetic data keeps the example hermetic.
"""

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd
from horovod_tpu.tensorflow.elastic import TensorFlowKerasState


def make_data(n=2048, d=64, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes)
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ w).argmax(-1).astype(np.int64)
    return x, y


def main():
    hvd.init()
    tf.random.set_seed(0)
    x, y = make_data()

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    base_lr = 1e-3
    opt = tf.keras.optimizers.Adam(base_lr * hvd.size())
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)

    @tf.function
    def training_step(images, labels):
        with tf.GradientTape() as tape:
            logits = model(images, training=True)
            loss_value = loss_fn(labels, logits)
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss_value, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        return loss_value

    # Materialise variables before building the elastic state so the
    # snapshot covers the full model + optimizer slot set.
    batch = 64
    training_step(x[:batch], y[:batch])

    state = TensorFlowKerasState(model, opt, batch=0, epoch=0)

    @hvd.elastic.run
    def train(state):
        # re-entered after every resize: keep lr proportional to the
        # CURRENT world size (reference analog: the on_state_reset
        # callback's opt.lr.assign)
        opt.learning_rate.assign(base_lr * hvd.size())
        for epoch in range(state.epoch, 3):
            loss_value = float("nan")  # a restore may land past the last step
            shard = np.arange(hvd.rank(), len(x), hvd.size())
            steps = len(shard) // batch
            for i in range(state.batch, steps):
                idx = shard[i * batch:(i + 1) * batch]
                loss_value = training_step(x[idx], y[idx])
                state.batch = i + 1
                if state.batch % 10 == 0:
                    state.commit()
            state.batch = 0
            state.epoch = epoch + 1
            state.commit()
            if hvd.rank() == 0:
                print(f"epoch {epoch} done, loss={float(loss_value):.4f} "
                      f"(world size {hvd.size()})")

    train(state)
    hvd.shutdown()


if __name__ == "__main__":
    main()
