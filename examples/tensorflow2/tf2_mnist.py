"""Drop-in TensorFlow 2 training with the TF adapter.

Run single-process:          python examples/tensorflow2/tf2_mnist.py
Run multi-process (2 ranks): hvdrun -np 2 python examples/tensorflow2/tf2_mnist.py

Reference analog: ``examples/tensorflow2/tensorflow2_mnist.py`` — change
``import horovod.tensorflow as hvd`` to ``import horovod_tpu.tensorflow as
hvd`` and keep the script: DistributedGradientTape averages gradients
across ranks, the first step broadcasts variables from rank 0, the loss
metric is allreduced. Synthetic data keeps it hermetic.
"""

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def make_data(n=4096, d=64, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes)
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ w + 0.1 * rng.randn(n, classes)).argmax(-1).astype(np.int64)
    return x, y


def main():
    hvd.init()
    tf.random.set_seed(42)

    x, y = make_data()
    ds = (tf.data.Dataset.from_tensor_slices((x, y))
          .shard(hvd.size(), max(hvd.rank(), 0))
          .shuffle(1024, seed=1).batch(128).repeat(3))

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(128, activation="tanh"),
        tf.keras.layers.Dense(10),
    ])
    loss_obj = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)
    opt = tf.keras.optimizers.SGD(learning_rate=0.05, momentum=0.9)

    def training_step(images, labels, first_batch):
        with tf.GradientTape() as tape:
            loss = loss_obj(labels, model(images, training=True))
        # DistributedGradientTape averages gradients across ranks
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if first_batch:
            # after the first apply so slot variables exist
            # (reference: tensorflow2_mnist.py broadcast on batch 0)
            hvd.broadcast_variables(model.variables, root_rank=0)
            opt_vars = opt.variables() if callable(opt.variables) \
                else opt.variables
            hvd.broadcast_variables(opt_vars, root_rank=0)
        return loss

    for step, (images, labels) in enumerate(ds):
        loss = training_step(images, labels, step == 0)
        if step % 20 == 0:
            avg = hvd.allreduce(loss, name="loss")
            if hvd.rank() == 0:
                print(f"step {step}: loss {float(avg):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
