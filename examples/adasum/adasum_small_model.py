"""Adasum gradient combination on a tiny curve-fitting model.

Run:  hvdrun -np 4 python examples/adasum/adasum_small_model.py

Reference analog: ``examples/adasum/adasum_small_model.py`` — fit a small
polynomial with per-rank disjoint data and combine gradients with
``op=hvd.Adasum`` (VHDD adaptive summation: scales each contribution by
how orthogonal it is to the others, so the effective LR adapts to the
world size instead of requiring manual LR scaling).
"""

import numpy as np
import torch

import horovod_tpu.torch as hvd


def target(x):
    return 10 * x ** 3 + 5 * x ** 2 - 20 * x - 5


def main():
    hvd.init()
    torch.manual_seed(hvd.rank())

    # each rank fits on a DIFFERENT slice of the input domain —
    # exactly the regime Adasum's orthogonality weighting is built for
    lo = -2.0 + 4.0 * max(hvd.rank(), 0) / max(hvd.size(), 1)
    x = torch.linspace(lo, lo + 4.0 / max(hvd.size(), 1), 256)
    y = target(x)

    param = torch.nn.Parameter(torch.tensor([1.0, -1.0, 1.0]))
    opt = torch.optim.SGD([param], lr=1e-3)

    hvd.broadcast_parameters({"param": param.data}, root_rank=0)

    for step in range(200):
        opt.zero_grad()
        pred = 10 * x ** 3 + param[0] * x ** 2 + param[1] * x + param[2]
        loss = torch.mean((pred - y) ** 2)
        loss.backward()
        # Adasum-combine the gradient across ranks (reference:
        # hvd.allreduce(..., op=hvd.Adasum))
        param.grad.data = hvd.allreduce(param.grad.data, op=hvd.Adasum,
                                        name="grad")
        opt.step()
        if step % 50 == 0:
            avg = hvd.allreduce(loss.detach(), name="loss")
            if hvd.rank() == 0:
                print(f"step {step}: loss {float(avg):.4f} "
                      f"param {param.data.tolist()}")
    if hvd.rank() == 0:
        print(f"final param {param.data.tolist()} (target [5, -20, -5])")
    hvd.shutdown()


if __name__ == "__main__":
    main()
