"""Spark ML estimator example: fit a torch model on a DataFrame with
distributed training, then score it with transform().

Reference analog: ``examples/spark/pytorch/pytorch_spark_mnist.py``
(TorchEstimator over a Spark DataFrame + Store). Works with a real Spark
session (DataFrames duck-type ``toPandas``) or plain pandas, as here.

    python examples/spark/estimator_regression.py [--np 2]
"""

import argparse
import tempfile

import numpy as np
import pandas as pd
import torch

from horovod_tpu.spark import LocalStore, TorchEstimator


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--np", type=int, default=2, dest="num_proc")
    p.add_argument("--epochs", type=int, default=10)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    X = rng.randn(512, 8).astype(np.float32)
    w = rng.randn(8).astype(np.float32)
    df = pd.DataFrame({f"x{i}": X[:, i] for i in range(8)})
    df["y"] = X @ w + 0.05 * rng.randn(512).astype(np.float32)

    est = TorchEstimator(
        model=torch.nn.Sequential(
            torch.nn.Linear(8, 32), torch.nn.ReLU(),
            torch.nn.Linear(32, 1)),
        optimizer="Adam", loss="MSELoss",
        feature_cols=[f"x{i}" for i in range(8)], label_cols=["y"],
        store=LocalStore(tempfile.mkdtemp(prefix="hvd_est_")),
        num_proc=args.num_proc, epochs=args.epochs, batch_size=64,
        learning_rate=1e-3, validation=0.1, verbose=1)

    model = est.fit(df)
    print("loss history:", [round(v, 4) for v in model.history["loss"]])
    print("val loss:   ", [round(v, 4)
                           for v in model.history.get("val_loss", [])])
    scored = model.transform(df.head(5))
    print(scored[["y", "y__output"]])


if __name__ == "__main__":
    main()
