"""Elastic training on a Spark cluster with ``spark.run_elastic``.

Run from a Spark driver (pyspark required):
    python examples/spark/elastic_run.py

Reference analog: ``horovod.spark.run_elastic`` (``spark/runner.py:309``)
— the training fn uses the ``hvd.elastic`` API exactly as it would under
``hvdrun``; Spark tasks host the worker processes, executor loss shrinks
the job, and Spark's task retry grows it back. Synthetic data keeps the
example hermetic.
"""

import numpy as np


def train():
    import horovod_tpu as hvd
    import horovod_tpu.elastic as elastic

    hvd.init()

    rng = np.random.RandomState(0)
    w_true = rng.randn(8, 1)
    x = rng.randn(512, 8).astype(np.float32)
    y = (x @ w_true).astype(np.float32)

    w = np.zeros((8, 1), np.float32)
    state = elastic.ObjectState(name="spark_elastic", w=w, step=0)

    @elastic.run
    def fit(state):
        lr = 0.1
        for step in range(state.step, 200):
            shard = np.arange(hvd.rank(), len(x), hvd.size())
            xb, yb = x[shard], y[shard]
            grad = 2 * xb.T @ (xb @ state.w - yb) / len(shard)
            gsum = hvd.allreduce(grad, op=hvd.Average, name="g")
            state.w = state.w - lr * np.asarray(gsum)
            state.step = step + 1
            if state.step % 50 == 0:
                state.commit()
        state.commit()

    fit(state)
    loss = float(np.mean((x @ state.w - y) ** 2))
    rank = hvd.rank()
    hvd.shutdown()
    return {"rank": rank, "loss": loss}


def main():
    import horovod_tpu.spark as spark

    results = spark.run_elastic(train, num_proc=2, min_np=1, max_np=4)
    print("per-rank results:", results)
    assert all(r["loss"] < 1e-3 for r in results)


if __name__ == "__main__":
    main()
