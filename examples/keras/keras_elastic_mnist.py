"""Elastic Keras training: model.fit + the elastic fit-callback trio.

Run with a changing world:
    hvdrun -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./discover.sh \
        python examples/keras/keras_elastic_mnist.py

Reference analog:
``examples/elastic/tensorflow2/tensorflow2_keras_mnist_elastic.py`` —
``@hvd.elastic.run`` retries a fit-based training function; the state
tracks model + optimizer + epoch/batch counters, and the callbacks keep
them current (Update* first, CommitStateCallback LAST). Synthetic data
keeps the example hermetic.
"""

import numpy as np
import tensorflow as tf

import horovod_tpu.keras as hvd


def make_data(n=2048, d=64, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes)
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ w).argmax(-1).astype(np.int64)
    return x, y


def main():
    hvd.init()
    tf.random.set_seed(0)
    x, y = make_data()

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    base_lr = 1e-3
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.Adam(base_lr * hvd.size()))
    model.compile(optimizer=opt,
                  loss=tf.keras.losses.SparseCategoricalCrossentropy(
                      from_logits=True),
                  run_eagerly=True)
    model.fit(x[:64], y[:64], epochs=1, batch_size=64, verbose=0)  # build

    state = hvd.elastic.KerasState(model, opt, epoch=0, batch=0)

    @hvd.elastic.run
    def train(state):
        opt.learning_rate.assign(base_lr * hvd.size())
        shard = np.arange(hvd.rank(), len(x), hvd.size())
        model.fit(
            x[shard], y[shard],
            epochs=3, initial_epoch=state.epoch, batch_size=64,
            verbose=1 if hvd.rank() == 0 else 0,
            callbacks=[
                hvd.callbacks.MetricAverageCallback(),
                hvd.elastic.UpdateBatchStateCallback(state),
                hvd.elastic.UpdateEpochStateCallback(state),
                hvd.elastic.CommitStateCallback(state,
                                                batches_per_commit=8),
            ])

    train(state)
    if hvd.rank() == 0:
        print(f"done at epoch {state.epoch} (world size {hvd.size()})")
    hvd.shutdown()


if __name__ == "__main__":
    main()
