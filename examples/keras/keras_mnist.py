"""Keras ``model.fit`` training with the Keras adapter.

Run single-process:          python examples/keras/keras_mnist.py
Run multi-process (2 ranks): hvdrun -np 2 python examples/keras/keras_mnist.py

Reference analog: ``examples/keras/keras_mnist.py`` — wrap the optimizer
with ``hvd.DistributedOptimizer``, scale the LR by world size, and plug in
the three callbacks (broadcast at start, metric averaging, LR warmup).
Synthetic data keeps it hermetic.
"""

import numpy as np
import tensorflow as tf

import horovod_tpu.keras as hvd


def make_data(n=4096, d=64, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes)
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ w + 0.1 * rng.randn(n, classes)).argmax(-1)
    return x, tf.keras.utils.to_categorical(y, classes)


def main():
    hvd.init()
    x, y = make_data()
    shard = slice(max(hvd.rank(), 0), None, hvd.size())
    x, y = x[shard], y[shard]

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(128, activation="tanh", input_shape=(64,)),
        tf.keras.layers.Dense(10, activation="softmax"),
    ])

    # reference recipe: scale LR by world size, warm it up over the first
    # epochs, and average gradients through the wrapped optimizer
    base_lr = 0.05
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=base_lr * hvd.size()))
    model.compile(optimizer=opt, loss="categorical_crossentropy",
                  metrics=["accuracy"], run_eagerly=True)

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        # initial_lr is the UNSCALED base rate: the callback itself ramps
        # base_lr -> base_lr * size over the warmup epochs
        hvd.callbacks.LearningRateWarmupCallback(
            base_lr, warmup_epochs=2,
            steps_per_epoch=len(x) // 128, verbose=hvd.rank() == 0),
    ]
    model.fit(x, y, batch_size=128, epochs=4,
              callbacks=callbacks, verbose=2 if hvd.rank() == 0 else 0)
    hvd.shutdown()


if __name__ == "__main__":
    main()
