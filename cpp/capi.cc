// extern "C" surface loaded from Python via ctypes (reference:
// horovod/common/operations.cc:869-1260 C API + basics.py ctypes wrapper).
#include <cstdlib>
#include <cstring>
#include <sstream>
#include <string>
#include <vector>

#include "core.h"
#include "wire.h"

using hvd::Core;
using hvd::CoreConfig;
using hvd::DataType;
using hvd::ReduceOp;

namespace {

thread_local std::string g_last_error;

int SetError(const hvd::Status& s) {
  g_last_error = s.reason;
  return -1;
}

const char* EnvOr(const char* a, const char* b, const char* dflt) {
  const char* v = getenv(a);
  if (v && *v) return v;
  v = getenv(b);
  if (v && *v) return v;
  return dflt;
}

}  // namespace

CoreConfig ParseEnvConfig() {
  CoreConfig cfg;
  cfg.rank = atoi(EnvOr("HVD_TPU_RANK", "HOROVOD_RANK", "0"));
  cfg.size = atoi(EnvOr("HVD_TPU_SIZE", "HOROVOD_SIZE", "1"));
  cfg.coord_addr = EnvOr("HVD_TPU_COORD_ADDR",
                         "HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1");
  cfg.coord_port = atoi(EnvOr("HVD_TPU_COORD_PORT",
                              "HOROVOD_GLOO_RENDEZVOUS_PORT", "37592"));
  cfg.fusion_threshold =
      atoll(EnvOr("HVD_TPU_FUSION_THRESHOLD", "HOROVOD_FUSION_THRESHOLD",
                  "67108864"));
  cfg.cycle_time_ms =
      atof(EnvOr("HVD_TPU_CYCLE_TIME", "HOROVOD_CYCLE_TIME", "1.0"));
  cfg.cache_capacity = (size_t)atoll(
      EnvOr("HVD_TPU_CACHE_CAPACITY", "HOROVOD_CACHE_CAPACITY", "1024"));
  cfg.stall_warning_secs = atof(EnvOr("HVD_TPU_STALL_CHECK_TIME_SECONDS",
                                      "HOROVOD_STALL_CHECK_TIME_SECONDS",
                                      "60"));
  cfg.autotune = atoi(EnvOr("HVD_TPU_AUTOTUNE", "HOROVOD_AUTOTUNE", "0"));
  cfg.disable_group_fusion = atoi(EnvOr("HVD_TPU_DISABLE_GROUP_FUSION",
                                        "HOROVOD_DISABLE_GROUP_FUSION",
                                        "0"));
  cfg.hierarchical_allgather = atoi(EnvOr("HVD_TPU_HIERARCHICAL_ALLGATHER",
                                          "HOROVOD_HIERARCHICAL_ALLGATHER",
                                          "0")) != 0;
  cfg.hierarchical_allreduce = atoi(EnvOr("HVD_TPU_HIERARCHICAL_ALLREDUCE",
                                          "HOROVOD_HIERARCHICAL_ALLREDUCE",
                                          "0"));
  cfg.local_rank = atoi(EnvOr("HVD_TPU_LOCAL_RANK", "HOROVOD_LOCAL_RANK",
                              "0"));
  cfg.local_size = atoi(EnvOr("HVD_TPU_LOCAL_SIZE", "HOROVOD_LOCAL_SIZE",
                              "1"));
  cfg.cross_rank = atoi(EnvOr("HVD_TPU_CROSS_RANK", "HOROVOD_CROSS_RANK",
                              "0"));
  cfg.cross_size = atoi(EnvOr("HVD_TPU_CROSS_SIZE", "HOROVOD_CROSS_SIZE",
                              "1"));
  cfg.timeline_path = EnvOr("HVD_TPU_TIMELINE", "HOROVOD_TIMELINE", "");
  cfg.timeline_mark_cycles = atoi(EnvOr("HVD_TPU_TIMELINE_MARK_CYCLES",
                                        "HOROVOD_TIMELINE_MARK_CYCLES",
                                        "0"));
  cfg.stall_shutdown_secs =
      atof(EnvOr("HVD_TPU_STALL_SHUTDOWN_TIME_SECONDS",
                 "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", "0"));
  if (atoi(EnvOr("HVD_TPU_STALL_CHECK_DISABLE",
                 "HOROVOD_STALL_CHECK_DISABLE", "0")))
    cfg.stall_warning_secs = 1e18;  // effectively disabled
  cfg.autotune_warmup_samples =
      atoi(EnvOr("HVD_TPU_AUTOTUNE_WARMUP_SAMPLES",
                 "HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "3"));
  cfg.autotune_max_samples =
      atoi(EnvOr("HVD_TPU_AUTOTUNE_BAYES_OPT_MAX_SAMPLES",
                 "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "24"));
  cfg.autotune_gp_noise =
      atof(EnvOr("HVD_TPU_AUTOTUNE_GAUSSIAN_PROCESS_NOISE",
                 "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", "1e-6"));
  cfg.autotune_log =
      EnvOr("HVD_TPU_AUTOTUNE_LOG", "HOROVOD_AUTOTUNE_LOG", "");
  cfg.autotune_window_secs =
      atof(EnvOr("HVD_TPU_AUTOTUNE_WINDOW_SECONDS",
                 "HOROVOD_AUTOTUNE_WINDOW_SECONDS", "2.0"));
  cfg.rendezvous_timeout_secs =
      atof(EnvOr("HVD_TPU_GLOO_TIMEOUT_SECONDS",
                 "HOROVOD_GLOO_TIMEOUT_SECONDS", "30"));
  cfg.thread_affinity = atoi(EnvOr("HVD_TPU_THREAD_AFFINITY",
                                   "HOROVOD_THREAD_AFFINITY", "-1"));
  cfg.straggler_report_secs =
      atof(EnvOr("HVD_TPU_STRAGGLER_REPORT_SECONDS",
                 "HOROVOD_STRAGGLER_REPORT_SECONDS", "0"));
  // inactivity deadline on transport receives (0 = wait forever); a
  // wedged peer then fails collectives -> HorovodInternalError -> the
  // elastic reset path, instead of hanging the job (docs/CHAOS.md)
  cfg.transport_timeout_secs =
      atof(EnvOr("HVD_TPU_TRANSPORT_TIMEOUT_S",
                 "HOROVOD_TRANSPORT_TIMEOUT_S", "0"));
  // per-frame CRC32C on the eager wire, default ON (docs/CHAOS.md
  // "Wire integrity"); must be set uniformly across the world — the
  // frame header grows a crc field when enabled
  cfg.wire_checksum =
      atoi(EnvOr("HVD_TPU_WIRE_CHECKSUM",
                 "HOROVOD_WIRE_CHECKSUM", "1")) != 0;
  return cfg;
}

extern "C" {

int hvd_init() {
  auto st = Core::Get().Init(ParseEnvConfig());
  if (!st.ok()) return SetError(st);
  return 0;
}

// Parsed-config dump for knob round-trip tests (key=value lines),
// serialized from the SAME parser hvd_init uses so the test exercises the
// engine's real env handling.
static std::string g_cfg_dump;
const char* hvd_cfg_dump() {
  CoreConfig c = ParseEnvConfig();
  std::ostringstream os;
  os << "fusion_threshold=" << c.fusion_threshold
     << "\ncycle_time_ms=" << c.cycle_time_ms
     << "\ncache_capacity=" << c.cache_capacity
     << "\nstall_warning_secs=" << c.stall_warning_secs
     << "\nstall_shutdown_secs=" << c.stall_shutdown_secs
     << "\nstraggler_report_secs=" << c.straggler_report_secs
     << "\nautotune=" << (c.autotune ? 1 : 0)
     << "\nautotune_warmup_samples=" << c.autotune_warmup_samples
     << "\nautotune_max_samples=" << c.autotune_max_samples
     << "\nautotune_gp_noise=" << c.autotune_gp_noise
     << "\nrendezvous_timeout_secs=" << c.rendezvous_timeout_secs
     << "\ntransport_timeout_s=" << c.transport_timeout_secs
     << "\nwire_checksum=" << (c.wire_checksum ? 1 : 0)
     << "\nthread_affinity=" << c.thread_affinity
     << "\ntimeline=" << c.timeline_path
     << "\ntimeline_mark_cycles=" << (c.timeline_mark_cycles ? 1 : 0)
     << "\nhierarchical_allreduce="
     << (c.hierarchical_allreduce ? 1 : 0)
     << "\ndisable_group_fusion=" << (c.disable_group_fusion ? 1 : 0)
     << "\n";
  g_cfg_dump = os.str();
  return g_cfg_dump.c_str();
}

void hvd_shutdown() { Core::Get().Shutdown(); }
void hvd_shutdown_force() { Core::Get().Shutdown(/*force=*/true); }

int hvd_initialized() { return Core::Get().initialized() ? 1 : 0; }
int hvd_rank() { return Core::Get().rank(); }
int hvd_size() { return Core::Get().size(); }

const char* hvd_last_error() { return g_last_error.c_str(); }

int hvd_enqueue_allreduce(const char* name, const void* in, void* out,
                          int dtype, int ndim, const int64_t* shape, int op,
                          double prescale, double postscale, int domain) {
  std::vector<int64_t> sh(shape, shape + ndim);
  return Core::Get().EnqueueAllreduce(domain, name, in, out,
                                      (DataType)dtype, sh, (ReduceOp)op,
                                      prescale, postscale);
}

int hvd_enqueue_grouped_allreduce(const char* name, const void* in,
                                  void* out, int dtype, int ndim,
                                  const int64_t* shape, int op,
                                  double prescale, double postscale,
                                  int domain, int group_id,
                                  int group_size) {
  std::vector<int64_t> sh(shape, shape + ndim);
  return Core::Get().EnqueueAllreduce(domain, name, in, out,
                                      (DataType)dtype, sh, (ReduceOp)op,
                                      prescale, postscale, group_id,
                                      group_size);
}

int hvd_enqueue_allgather(const char* name, const void* in, int dtype,
                          int ndim, const int64_t* shape, int domain) {
  std::vector<int64_t> sh(shape, shape + ndim);
  return Core::Get().EnqueueAllgather(domain, name, in, (DataType)dtype, sh);
}

int hvd_enqueue_broadcast(const char* name, const void* in, void* out,
                          int root, int dtype, int ndim,
                          const int64_t* shape, int domain) {
  std::vector<int64_t> sh(shape, shape + ndim);
  return Core::Get().EnqueueBroadcast(domain, name, in, out, root,
                                      (DataType)dtype, sh);
}

int hvd_enqueue_alltoall(const char* name, const void* in,
                         const int64_t* splits, int nsplits, int dtype,
                         int ndim, const int64_t* shape, int domain) {
  std::vector<int64_t> sp(splits, splits + nsplits);
  std::vector<int64_t> sh(shape, shape + ndim);
  return Core::Get().EnqueueAlltoall(domain, name, in, sp, (DataType)dtype,
                                     sh);
}

int hvd_enqueue_join(int domain) { return Core::Get().EnqueueJoin(domain); }

int hvd_barrier(int domain) {
  auto st = Core::Get().ExecBarrier(domain);
  if (!st.ok()) return SetError(st);
  return 0;
}

int hvd_poll(int handle) { return Core::Get().Poll(handle) ? 1 : 0; }

int hvd_wait(int handle, double timeout_s) {
  auto st = Core::Get().WaitHandle(handle, timeout_s);
  if (st.type == hvd::StatusType::kInProgress) {
    g_last_error = st.reason;
    return -2;  // timeout: handle remains valid, caller may retry
  }
  if (!st.ok()) return SetError(st);
  return 0;
}

// For variable-size results: query ndim then shape, then copy.
int hvd_result_ndim(int handle) {
  return (int)Core::Get().ResultShape(handle).size();
}

int hvd_result_shape(int handle, int64_t* out, int max_ndim) {
  auto s = Core::Get().ResultShape(handle);
  int n = (int)std::min((size_t)max_ndim, s.size());
  for (int i = 0; i < n; ++i) out[i] = s[i];
  return n;
}

int hvd_recv_splits(int handle, int64_t* out, int max_n) {
  auto s = Core::Get().RecvSplits(handle);
  int n = (int)std::min((size_t)max_n, s.size());
  for (int i = 0; i < n; ++i) out[i] = s[i];
  return n;
}

int hvd_copy_result(int handle, void* dst, int64_t max_bytes) {
  auto st = Core::Get().CopyResult(handle, dst, max_bytes);
  if (!st.ok()) return SetError(st);
  return 0;
}

void hvd_free_handle(int handle) { Core::Get().FreeHandle(handle); }

int hvd_add_process_set(const int* ranks, int n) {
  std::vector<int> r(ranks, ranks + n);
  return Core::Get().AddProcessSet(r);
}

void hvd_remove_process_set(int id) { Core::Get().RemoveProcessSet(id); }

int hvd_last_join_rank(int domain) {
  return Core::Get().last_join_rank(domain);
}

// Dynamic timeline control (reference: horovod_start_timeline /
// horovod_stop_timeline, operations.cc:1011-1041). Coordinator-only file;
// non-zero ranks no-op and return 0.
int hvd_start_timeline(const char* path, int mark_cycles) {
  auto st = Core::Get().StartTimeline(path ? path : "", mark_cycles != 0);
  if (!st.ok()) return SetError(st);
  return 0;
}

int hvd_stop_timeline() {
  auto st = Core::Get().StopTimeline();
  if (!st.ok()) return SetError(st);
  return 0;
}

// CRC32C of a buffer — the exact function the wire integrity check runs
// per frame (cpp/wire.h), exported so the Python unit battery can hold
// it to the published Castagnoli test vectors without a 2-process run.
unsigned int hvd_crc32c(const void* data, long long n) {
  return hvd::wire::Crc32c(data, (size_t)n);
}

// Control-plane counters as one JSON object (steady-state observability:
// cache-hit rate, fusion effectiveness, negotiation volume).
// thread_local: concurrent callers each keep their own buffer, and the
// returned pointer stays valid until the SAME thread calls again
static thread_local std::string g_counters_json;
const char* hvd_counters_json() {
  const auto& c = Core::Get().counters();
  std::ostringstream os;
  os << "{\"cycles\":" << c.cycles.load()
     << ",\"cache_hits\":" << c.cache_hits.load()
     << ",\"cache_misses\":" << c.cache_misses.load()
     << ",\"cache_evictions\":" << c.cache_evictions.load()
     << ",\"responses_executed\":" << c.responses_executed.load()
     << ",\"tensors_fused\":" << c.tensors_fused.load()
     << ",\"fused_units\":" << c.fused_units.load()
     << ",\"bytes_allreduced\":" << c.bytes_allreduced.load()
     << ",\"bytes_allgathered\":" << c.bytes_allgathered.load()
     << ",\"hier_allreduces\":" << c.hier_allreduces.load()
     << ",\"hier_allgathers\":" << c.hier_allgathers.load()
     << ",\"stall_warnings\":" << c.stall_warnings.load()
     << ",\"stalled_tensors\":" << c.stalled_tensors.load()
     << ",\"transport_chaos_injected\":"
     << c.transport_chaos_injected.load()
     << ",\"transport_checksum_failures\":"
     << c.transport_checksum_failures.load()
     << ",\"autotune_fusion_bytes\":" << c.autotune_fusion_bytes.load()
     << ",\"autotune_cycle_ms\":"
     << (c.autotune_cycle_us.load() / 1000.0)
     << ",\"autotune_hierarchical\":" << c.autotune_hierarchical.load()
     << ",\"autotune_cache_enabled\":"
     << c.autotune_cache_enabled.load() << "}";
  g_counters_json = os.str();
  return g_counters_json.c_str();
}

// Coordinator-side straggler report as one JSON object: per-rank totals of
// negotiation wait charged to the last-announcing rank (who held whom up).
// Non-coordinator ranks accumulate nothing and return an empty report.
static thread_local std::string g_stragglers_json;
const char* hvd_stragglers_json() {
  g_stragglers_json = Core::Get().StragglersJson();
  return g_stragglers_json.c_str();
}

// Engine-state snapshot for hang autopsies: per-domain pending tensors
// with ready/missing ranks, queue depth, join state (the stall
// inspector's view, serialized — the reference only LOGS this). The
// loop thread publishes it; this returns the latest copy, so it stays
// readable from any thread even mid-hang.
static thread_local std::string g_engine_state_json;
const char* hvd_engine_state_json() {
  g_engine_state_json = Core::Get().EngineStateJson();
  return g_engine_state_json.c_str();
}

// Span plumbing for the diagnostics cross-rank trace: the Python eager
// layer stamps its per-collective span id into the engine timeline as
// an instant marker, correlating the host shard with the negotiation
// trace without any wire traffic.
int hvd_timeline_enabled() {
  return Core::Get().TimelineEnabled() ? 1 : 0;
}

void hvd_timeline_mark(const char* name, const char* span) {
  Core::Get().TimelineMark(name ? name : "", span ? span : "");
}

}  // extern "C"
