// Compact binary wire format for Request/Response lists.
//
// Replaces the reference's FlatBuffers schema (horovod/common/wire/
// message.fbs + message_generated.h): control messages here are small and
// point-to-point on a trusted cluster network, so a hand-rolled
// length-prefixed encoding avoids the third-party dependency entirely.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "types.h"

namespace hvd {
namespace wire {

// CRC32C (Castagnoli, the iSCSI/ext4 polynomial) — the per-frame wire
// integrity check of the eager TCP data plane (HVD_TPU_WIRE_CHECKSUM,
// docs/CHAOS.md "Wire integrity").  Software table implementation: the
// eager path moves host tensors, so the ~1 GB/s table walk is never the
// bottleneck next to the TCP stack, and it needs no SSE4.2 dispatch.
// Chainable: pass the previous return value as `crc` to extend a digest
// over multiple buffers (header + payload).
struct Crc32cTable {
  uint32_t t[256];
  Crc32cTable() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
      t[i] = c;
    }
  }
};

inline uint32_t Crc32c(const void* data, size_t n, uint32_t crc = 0) {
  static const Crc32cTable table;
  const uint8_t* p = (const uint8_t*)data;
  crc = ~crc;
  for (size_t i = 0; i < n; ++i)
    crc = table.t[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

class Writer {
 public:
  std::vector<uint8_t> buf;
  void u8(uint8_t v) { buf.push_back(v); }
  void i32(int32_t v) { append(&v, 4); }
  void i64(int64_t v) { append(&v, 8); }
  void f64(double v) { append(&v, 8); }
  void str(const std::string& s) {
    i32((int32_t)s.size());
    append(s.data(), s.size());
  }
  void shape(const std::vector<int64_t>& s) {
    i32((int32_t)s.size());
    for (auto d : s) i64(d);
  }
  void append(const void* p, size_t n) {
    auto* b = (const uint8_t*)p;
    buf.insert(buf.end(), b, b + n);
  }
};

class Reader {
 public:
  const uint8_t* p;
  size_t len, off = 0;
  Reader(const uint8_t* data, size_t n) : p(data), len(n) {}
  uint8_t u8() { return p[off++]; }
  int32_t i32() { int32_t v; memcpy(&v, p + off, 4); off += 4; return v; }
  int64_t i64() { int64_t v; memcpy(&v, p + off, 8); off += 8; return v; }
  double f64() { double v; memcpy(&v, p + off, 8); off += 8; return v; }
  std::string str() {
    int32_t n = i32();
    std::string s((const char*)p + off, n);
    off += n;
    return s;
  }
  std::vector<int64_t> shape() {
    int32_t n = i32();
    std::vector<int64_t> s(n);
    for (auto& d : s) d = i64();
    return s;
  }
};

inline void EncodeRequest(Writer& w, const Request& r) {
  w.i32(r.type);
  w.i32(r.rank);
  w.str(r.name);
  w.i32((int32_t)r.dtype);
  w.shape(r.shape);
  w.i32(r.root_rank);
  w.i32((int32_t)r.op);
  w.f64(r.prescale);
  w.f64(r.postscale);
  w.i32(r.group_id);
  w.i32(r.group_size);
}

inline Request DecodeRequest(Reader& rd) {
  Request r;
  r.type = (Request::Type)rd.i32();
  r.rank = rd.i32();
  r.name = rd.str();
  r.dtype = (DataType)rd.i32();
  r.shape = rd.shape();
  r.root_rank = rd.i32();
  r.op = (ReduceOp)rd.i32();
  r.prescale = rd.f64();
  r.postscale = rd.f64();
  r.group_id = rd.i32();
  r.group_size = rd.i32();
  return r;
}

inline void EncodeResponse(Writer& w, const Response& r) {
  w.u8(r.from_cache ? 1 : 0);
  w.i32(r.type);
  w.i32((int32_t)r.names.size());
  for (auto& n : r.names) w.str(n);
  w.str(r.error_message);
  w.i32((int32_t)r.dtypes.size());
  for (auto d : r.dtypes) w.i32((int32_t)d);
  w.i32((int32_t)r.shapes.size());
  for (auto& s : r.shapes) w.shape(s);
  w.i32(r.root_rank);
  w.i32((int32_t)r.op);
  w.f64(r.prescale);
  w.f64(r.postscale);
  w.i32(r.last_joined_rank);
  w.i32(r.group_id);
  w.i32(r.group_size);
}

inline Response DecodeResponse(Reader& rd) {
  Response r;
  r.from_cache = rd.u8() != 0;
  r.type = (Response::Type)rd.i32();
  int32_t n = rd.i32();
  r.names.resize(n);
  for (auto& s : r.names) s = rd.str();
  r.error_message = rd.str();
  n = rd.i32();
  r.dtypes.resize(n);
  for (auto& d : r.dtypes) d = (DataType)rd.i32();
  n = rd.i32();
  r.shapes.resize(n);
  for (auto& s : r.shapes) s = rd.shape();
  r.root_rank = rd.i32();
  r.op = (ReduceOp)rd.i32();
  r.prescale = rd.f64();
  r.postscale = rd.f64();
  r.last_joined_rank = rd.i32();
  r.group_id = rd.i32();
  r.group_size = rd.i32();
  return r;
}

// Process-set registration announcement piggybacked on domain-0 negotiate
// messages: (domain id, hash of the member-rank list). New domains stay
// INACTIVE until the domain-0 coordinator has seen every rank announce them
// (reference: dynamic process-set registration is coordinated through the
// background thread, operations.cc:587-623) — without this, a member that
// starts the lockstep negotiation of a fresh set before a peer registered
// it deadlocks the whole cycle.
struct DomainAnnounce {
  int32_t id = 0;
  uint64_t ranks_hash = 0;
};

inline std::vector<uint8_t> EncodeRequestList(
    const std::vector<Request>& reqs, bool shutdown,
    const std::vector<int32_t>& cache_bits,
    const std::vector<DomainAnnounce>& announce = {},
    const std::vector<int32_t>& retire = {}) {
  Writer w;
  w.u8(shutdown ? 1 : 0);
  w.i32((int32_t)cache_bits.size());
  for (auto b : cache_bits) w.i32(b);
  w.i32((int32_t)announce.size());
  for (auto& a : announce) {
    w.i32(a.id);
    w.i64((int64_t)a.ranks_hash);
  }
  w.i32((int32_t)retire.size());
  for (auto r : retire) w.i32(r);
  w.i32((int32_t)reqs.size());
  for (auto& r : reqs) EncodeRequest(w, r);
  return std::move(w.buf);
}

inline std::vector<Request> DecodeRequestList(
    const uint8_t* p, size_t n, bool* shutdown,
    std::vector<int32_t>* cache_bits,
    std::vector<DomainAnnounce>* announce = nullptr,
    std::vector<int32_t>* retire = nullptr) {
  Reader rd(p, n);
  *shutdown = rd.u8() != 0;
  int32_t nb = rd.i32();
  cache_bits->resize(nb);
  for (auto& b : *cache_bits) b = rd.i32();
  int32_t na = rd.i32();
  for (int i = 0; i < na; ++i) {
    DomainAnnounce a;
    a.id = rd.i32();
    a.ranks_hash = (uint64_t)rd.i64();
    if (announce) announce->push_back(a);
  }
  int32_t nr = rd.i32();
  for (int i = 0; i < nr; ++i) {
    int32_t r = rd.i32();
    if (retire) retire->push_back(r);
  }
  int32_t cnt = rd.i32();
  std::vector<Request> reqs(cnt);
  for (auto& r : reqs) r = DecodeRequest(rd);
  return reqs;
}

inline std::vector<uint8_t> EncodeResponseList(
    const std::vector<Response>& rs, int64_t fusion_threshold,
    const std::vector<int32_t>& activate = {},
    const std::vector<int32_t>& retired = {},
    uint8_t knob_flags = 0x2) {
  Writer w;
  w.i64(fusion_threshold);  // coordinator's (possibly autotuned) value
  // autotuned categorical knobs (bit0 hierarchical, bit1 cache): ride the
  // response list so every rank flips at the same cycle boundary
  w.i32((int32_t)knob_flags);
  w.i32((int32_t)activate.size());
  for (auto a : activate) w.i32(a);
  w.i32((int32_t)retired.size());
  for (auto r : retired) w.i32(r);
  w.i32((int32_t)rs.size());
  for (auto& r : rs) EncodeResponse(w, r);
  return std::move(w.buf);
}

inline std::vector<Response> DecodeResponseList(
    const uint8_t* p, size_t n, int64_t* fusion_threshold,
    std::vector<int32_t>* activate = nullptr,
    std::vector<int32_t>* retired = nullptr,
    uint8_t* knob_flags = nullptr) {
  Reader rd(p, n);
  *fusion_threshold = rd.i64();
  int32_t kf = rd.i32();
  if (knob_flags) *knob_flags = (uint8_t)kf;
  int32_t na = rd.i32();
  for (int i = 0; i < na; ++i) {
    int32_t v = rd.i32();
    if (activate) activate->push_back(v);
  }
  int32_t nr = rd.i32();
  for (int i = 0; i < nr; ++i) {
    int32_t v = rd.i32();
    if (retired) retired->push_back(v);
  }
  int32_t cnt = rd.i32();
  std::vector<Response> rs(cnt);
  for (auto& r : rs) r = DecodeResponse(rd);
  return rs;
}

}  // namespace wire
}  // namespace hvd
