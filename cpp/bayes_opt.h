// Gaussian-process Bayesian optimization for the autotuner.
//
// Reference: horovod/common/optim/gaussian_process.cc (RBF-kernel GP with
// Cholesky solves) + bayesian_optimization.cc (expected-improvement
// acquisition maximized over candidates), driving ParameterManager's
// (fusion threshold, cycle time) search. Same design, dependency-free
// (the reference pulls in Eigen + LBFGS; a candidate-grid argmax over EI
// is ample for a 2-D space).
#pragma once

#include <cstdint>
#include <random>
#include <vector>

namespace hvd {

class GaussianProcess {
 public:
  // x: normalized points in [0,1]^d; y: scores (higher better)
  void Fit(const std::vector<std::vector<double>>& x,
           const std::vector<double>& y, double noise = 1e-6);
  // posterior mean/variance at x*
  void Predict(const std::vector<double>& xs, double* mu,
               double* var) const;

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;
  std::vector<std::vector<double>> x_;
  std::vector<double> alpha_;               // K^-1 (y - mean)
  std::vector<std::vector<double>> chol_;   // L of K + noise I
  double mean_ = 0;
  double length_scale_ = 0.3;
  double signal_var_ = 1.0;
};

class BayesianOptimizer {
 public:
  explicit BayesianOptimizer(int dims, uint64_t seed = 17,
                             double gp_noise = 1e-6);
  void AddSample(const std::vector<double>& x, double y);
  // next point to evaluate: argmax expected improvement over random
  // candidates (plus pure exploration until enough samples exist)
  std::vector<double> NextSample();
  std::vector<double> BestSample() const;
  int num_samples() const { return (int)y_.size(); }

 private:
  int dims_;
  std::mt19937_64 rng_;
  double gp_noise_;
  std::vector<std::vector<double>> x_;
  std::vector<double> y_;
};

}  // namespace hvd
